"""Full-potential LAPW self-consistency driver.

Reference: src/dft/dft_ground_state.cpp specialized to
electronic_structure_method = full_potential_lapwlo — the FP branch of
Density::generate, Potential::generate (Weinert Poisson + MT XC) and
Band::solve (diagonalize_fp). Total-energy bookkeeping follows
src/dft/energy.cpp:

  veff  = int rho v_eff        (MT lm sums + step-function interstitial)
  vha   = int rho v_H          (v_H includes the nuclear Coulomb)
  kin   = eval_sum - veff
  enuc  = -(1/2) sum_a Z_a v_el(r_a)   (regular Hartree at the nucleus)
  total = kin + exc + (1/2) vha + enuc

The SCF state mixed between iterations is the packed density
[rho_i(G) | rho_mt per atom]; plain l2 metric.
"""

from __future__ import annotations

import time

import numpy as np

from sirius_tpu.lapw.quad import radial_weights, rint

from sirius_tpu.config.schema import load_config
from sirius_tpu.core.fftgrid import FFTGrid
from sirius_tpu.core.gvec import Gvec, _enumerate_sphere, reciprocal_lattice
from sirius_tpu.core.sht import num_lm
from sirius_tpu.crystal.symmetry import CrystalSymmetry
from sirius_tpu.crystal.kpoints import irreducible_kmesh
from sirius_tpu.dft.mixer import Mixer
from sirius_tpu.dft.occupation import find_fermi
from sirius_tpu.dft.xc import XCFunctional
from sirius_tpu.lapw.basis import build_radial_basis, matching_coefficients
from sirius_tpu.lapw.density_fp import (
    atom_lo_cols,
    free_atom_rho_g,
    free_atom_rho_mt,
    interstitial_density_box,
    mt_density_from_dm,
    mt_expansion_coeffs,
    mt_index,
)
from sirius_tpu.lapw.fv import assemble_fv, diagonalize_fv
from sirius_tpu.lapw.poisson_fp import (
    mt_coulomb_potential,
    mt_multipoles,
    pseudo_density_g,
    pw_sphere_multipoles,
    sphere_boundary_lm,
    interstitial_potential_g,
)
from sirius_tpu.lapw.species import FpSpecies, step_function_g
from sirius_tpu.lapw.xc_fp import MtSht, gcart_box, interstitial_xc, mt_xc

Y00 = 1.0 / np.sqrt(4.0 * np.pi)


class FpContext:
    """Composition root for a full-potential run (FP analog of
    SimulationContext; reference Simulation_context FP branches)."""

    def __init__(self, cfg, base_dir: str = "."):
        import os

        p = cfg.parameters
        uc = cfg.unit_cell
        self.cfg = cfg
        a = np.asarray(uc.lattice_vectors, float) * uc.lattice_vectors_scale
        self.lattice = a
        self.omega = float(abs(np.linalg.det(a)))
        self.recip = reciprocal_lattice(a)

        self.species = {}
        for label in uc.atom_types:
            fname = uc.atom_files.get(label, f"{label}.json")
            self.species[label] = FpSpecies.from_file(
                label, os.path.join(base_dir, fname)
            )
        self.labels = []
        pos, moments = [], []
        units = uc.atom_coordinate_units
        bohr_radius = 0.52917721067  # reference core/constants.hpp:28
        for label in uc.atom_types:
            for v in uc.atoms.get(label, []):
                x = np.asarray(v[:3], float)
                if units == "A":
                    x = x / bohr_radius
                if units in ("A", "au"):
                    x = x @ np.linalg.inv(a)  # cartesian -> fractional
                pos.append(np.mod(x, 1.0))
                moments.append(
                    np.asarray(v[3:6], float) if len(v) >= 6 else np.zeros(3)
                )
                self.labels.append(label)
        self.positions = np.asarray(pos)
        self.moments = np.asarray(moments)
        self.num_mag_dims = p.num_mag_dims
        self.species_of_atom = [self.species[l] for l in self.labels]
        self.zn_tot = sum(sp.zn for sp in self.species_of_atom)

        if p.auto_rmt:
            self._auto_rmt(p.auto_rmt, cfg.control.rmt_max)
        self.rmt = np.asarray([sp.rmt for sp in self.species_of_atom])

        self.lmax_apw = p.lmax_apw
        self.lmax_rho = p.lmax_rho
        self.lmax_pot = p.lmax_pot
        self.gk_cutoff = (
            p.gk_cutoff if p.gk_cutoff > 0 else p.aw_cutoff / self.rmt.min()
        )

        # fine (density/potential) G set — the reference's exact box sizing
        # (interstitial XC/integrals are evaluated on this box, so its size
        # is part of the numerical definition; see FFTGrid.ref_min_grid)
        fft = FFTGrid.ref_min_grid(a, p.pw_cutoff)
        self.gvec = Gvec.build(a, p.pw_cutoff, fft=fft)
        self.dims = fft.dims
        self.theta_g = step_function_g(
            a, self.positions, self.rmt, self.gvec.gcart, self.gvec.millers
        )
        n = np.prod(self.dims)
        box = np.zeros(self.dims, dtype=np.complex128).ravel()
        box[self.gvec.fft_index] = self.theta_g
        self.theta_r = np.real(np.fft.ifftn(box.reshape(self.dims)) * n)

        # k-mesh
        self.sym = CrystalSymmetry.find(
            a, self.positions, np.asarray([hash(l) for l in self.labels]),
            moments=self.moments if p.num_mag_dims else None,
            num_mag_dims=p.num_mag_dims,
        ) if p.use_symmetry else None
        self.kpoints, self.kweights = irreducible_kmesh(
            p.ngridk, p.shiftk, self.sym, use_symmetry=p.use_symmetry
        )
        # APW |G+k| spheres (ragged; host assembly)
        self.gkmill = [
            _enumerate_sphere(self.recip, np.asarray(k), self.gk_cutoff, fft)
            for k in self.kpoints
        ]

        self.num_fv_states = (
            p.num_fv_states
            if p.num_fv_states > 0
            else max(int(self.zn_tot / 2) + 10, 4)
        )
        # core electrons per atom from the species' core string
        self.core_occ = [
            sum(occ for (_, _, occ) in sp.core_states())
            for sp in self.species_of_atom
        ]
        self.num_valence = self.zn_tot - sum(self.core_occ) + (
            -p.extra_charge if hasattr(p, "extra_charge") else 0.0
        )
        self.sht = MtSht(self.lmax_rho, self.lmax_pot)
        self.xc = XCFunctional(p.xc_functionals)

    def _auto_rmt(self, mode: int, rmt_max: float) -> None:
        """Recompute MT radii from nearest-neighbour distances and rebuild
        the species' radial grids (reference Unit_cell::find_mt_radii,
        unit_cell.cpp:30, auto_rmt = 1 with inflate = true)."""
        assert mode == 1, f"auto_rmt mode {mode} not implemented"
        nat = len(self.positions)
        types = list(dict.fromkeys(self.labels))
        tid = {lab: i for i, lab in enumerate(types)}
        # nearest neighbour over periodic images (+-2 covers moderately
        # skewed / non-reduced cells; the reference does a radius search)
        rng2 = (-2, -1, 0, 1, 2)
        img = np.array([[i, j, k] for i in rng2 for j in rng2 for k in rng2])
        nn_d = np.full(nat, np.inf)
        nn_j = np.zeros(nat, dtype=int)
        for ia in range(nat):
            for ja in range(nat):
                d = (self.positions[ja] + img - self.positions[ia]) @ self.lattice
                dist = np.linalg.norm(d, axis=1)
                dist[dist < 1e-10] = np.inf  # exclude self at zero shift
                jmin = np.argmin(dist)
                if dist[jmin] < nn_d[ia]:
                    nn_d[ia] = dist[jmin]
                    nn_j[ia] = ja
        ntyp = len(types)
        Rmt = np.full(ntyp, 1e10)
        for ia in range(nat):
            id1, id2 = tid[self.labels[ia]], tid[self.labels[nn_j[ia]]]
            R = min(rmt_max, 0.95 * nn_d[ia] / 2)
            Rmt[id1] = min(Rmt[id1], R)
            Rmt[id2] = min(Rmt[id2], R)
        # inflate pass: types whose spheres are far from touching may expand
        # toward already-fixed neighbours
        scale_ok = np.ones(ntyp, dtype=bool)
        for ia in range(nat):
            id1, id2 = tid[self.labels[ia]], tid[self.labels[nn_j[ia]]]
            if Rmt[id1] + Rmt[id2] > nn_d[ia] * 0.94:
                scale_ok[id1] = scale_ok[id2] = False
        Rmt_infl = np.full(ntyp, 1e10)
        for ia in range(nat):
            id1, id2 = tid[self.labels[ia]], tid[self.labels[nn_j[ia]]]
            if scale_ok[id1] and not scale_ok[id2]:
                Rmt_infl[id1] = min(
                    Rmt_infl[id1], min(rmt_max, 0.95 * (nn_d[ia] - Rmt[id2]))
                )
            else:
                Rmt_infl[id1] = min(Rmt_infl[id1], Rmt[id1])
        for lab in types:
            sp = self.species[lab]
            R = float(Rmt_infl[tid[lab]])
            if R < 0.3:
                raise ValueError(f"auto rmt too small for {lab}: {R}")
            sp.rmt = R
            sp.r = sp.rmin * (R / sp.rmin) ** (
                np.arange(sp.nrmt) / (sp.nrmt - 1.0)
            )

    def mt_integral(self, f_lm_by_atom, g_lm_by_atom) -> float:
        """sum_a sum_lm int f_lm g_lm r^2 dr (real-harmonic orthonormality)."""
        out = 0.0
        for sp, f, g in zip(self.species_of_atom, f_lm_by_atom, g_lm_by_atom):
            nlm = min(f.shape[0], g.shape[0])
            out += float(
                rint(
                    np.sum(f[:nlm] * g[:nlm], axis=0) * sp.r**2, sp.r
                )
            )
        return out

    def g2r(self, f_g: np.ndarray) -> np.ndarray:
        """Real-space box from fine-G-set coefficients."""
        n = int(np.prod(self.dims))
        box = np.zeros(n, dtype=np.complex128)
        box[self.gvec.fft_index] = f_g
        return np.real(np.fft.ifftn(box.reshape(self.dims)) * n)

    def istl_integral(self, f_r, g_r) -> float:
        """(Omega/N) sum_r f g theta — interstitial region integral."""
        n = np.prod(self.dims)
        return float(self.omega / n * np.sum(f_r * g_r * self.theta_r))


def core_states_density(sp, v_sph, rel: str = "dirac"):
    """Core density [nr] (per volume, spherical) + eigenvalue sum + charge
    leak outside the sphere. Solved on the MT grid extended by the
    free-atom tail potential -Z_ion/r (reference atom_symmetry_class
    generate_core_charge_density on the free-atom grid)."""
    from sirius_tpu.lapw.radial_solver import (
        find_bound_state,
        find_bound_state_dirac,
    )

    if not sp.core_states():
        return np.zeros_like(sp.r), 0.0, 0.0
    e_floor = -0.6 * sp.zn**2 - 10.0  # brackets 1s for any Z
    # extended grid + potential tail alpha/r + beta matching the ELECTRONIC
    # part's value and derivative at R (reference
    # atom_symmetry_class.cpp:781-810 generate_core_charge_density)
    r_mt = sp.r
    R = r_mt[-1]
    ext = []
    x = R
    dx = r_mt[-1] - r_mt[-2]
    while x < 30.0 + sp.zn / 4.0:
        x += dx
        ext.append(x)
        dx *= 1.025
    r_ext = np.asarray(ext)
    r = np.concatenate([r_mt, r_ext])
    svmt = v_sph + sp.zn / r_mt  # electronic part (nucleus removed)
    # boundary slope via the cubic spline (reference svmt.deriv(1, nmtp-1),
    # atom_symmetry_class.cpp:799) — a finite difference here shifts the
    # alpha/r tail and with it the semicore eigenvalues at the mHa scale
    from sirius_tpu.core.radial import Spline

    dsv = float(Spline(r_mt, svmt).derivative(r_mt[-1]))
    alpha = -(R * R * dsv + sp.zn)
    beta = svmt[-1] - (sp.zn + alpha) / R
    v = np.concatenate([v_sph, alpha / r_ext + beta])
    # deep-core eigenvalues need better than the basis grid's RK4 step;
    # the bound-state solvers refine internally (radial_solver._refine_grid,
    # refine=1 default — the reference reaches the same accuracy class with
    # its adaptive GSL integrator, radial_solver.hpp:344)
    nmt = len(r_mt)
    rho = np.zeros_like(r)
    esum = 0.0
    for (nql, l, occ) in sp.core_states():
        if rel == "dirac":
            # both j = l +- 1/2 branches, degeneracy-weighted
            etot, utot = 0.0, np.zeros_like(r)
            for kappa in ([-1] if l == 0 else [l, -l - 1]):
                deg = 2 * abs(kappa)
                e, g, f = find_bound_state_dirac(r, v, nql, kappa)
                etot += deg * e
                utot += deg * (g**2 + f**2)
            frac = occ / (2.0 * (2 * l + 1))
            esum += frac * etot
            rho += frac * utot / (4.0 * np.pi)
        else:
            e, u = find_bound_state(r, v, l, nql, rel=rel, e_lo=e_floor)
            esum += occ * e
            rho += occ * u**2 / (4.0 * np.pi)
    rho_mt_out = rho[:nmt]
    leak = 4.0 * np.pi * np.trapezoid(
        rho[nmt - 1 :] * r[nmt - 1 :] ** 2, r[nmt - 1 :]
    )
    return rho_mt_out, esum, leak


def run_scf_fp(cfg, base_dir: str = ".") -> dict:
    """Ground state of a full-potential LAPW deck; returns the reference-
    shaped result dict (reference dft_ground_state.find + json output)."""
    t0 = time.time()
    p = cfg.parameters
    ctx = FpContext(cfg, base_dir)
    nat = len(ctx.positions)
    lmmax_pot = num_lm(ctx.lmax_pot)
    nev = ctx.num_fv_states
    rel_core = p.core_relativity
    rel_val = p.valence_relativity

    nm = p.num_mag_dims
    if nm not in (0, 1):
        raise NotImplementedError("FP-LAPW: only collinear magnetism so far")
    ns = 2 if nm else 1

    # ---- initial density: free-atom superposition ----
    rho_mt = [free_atom_rho_mt(sp, ctx.lmax_rho) for sp in ctx.species_of_atom]
    rho_ig = free_atom_rho_g(
        ctx.species_of_atom, ctx.positions, ctx.gvec.millers, ctx.gvec.gcart,
        ctx.omega,
    )
    mag_mt = None
    mag_ig = np.zeros_like(rho_ig) if nm else None
    if nm:
        # scale the atomic density to carry the requested sphere moment
        # (reference Density::initial_density mag branch)
        mag_mt = []
        for ia, sp in enumerate(ctx.species_of_atom):
            q = np.sqrt(4 * np.pi) * float(rint(rho_mt[ia][0] * sp.r**2, sp.r))
            mz = float(ctx.moments[ia][2])
            mz = np.clip(mz, -q, q)
            mag_mt.append(rho_mt[ia] * (mz / max(q, 1e-12)))

    def pack(rho_ig, rho_mt, mag_ig=None, mag_mt=None):
        parts = [rho_ig.view(float)]
        if nm:
            parts.append(mag_ig.view(float))
        parts += [m.ravel() for m in rho_mt]
        if nm:
            parts += [m.ravel() for m in mag_mt]
        return np.concatenate(parts)

    def unpack(x):
        ngf = 2 * ctx.gvec.num_gvec
        ig = x[:ngf].view(complex)
        off = ngf
        mig = None
        if nm:
            mig = x[off : off + ngf].view(complex)
            off += ngf
        lmmax_rho = num_lm(ctx.lmax_rho)
        mts = []
        for sp in ctx.species_of_atom:
            sz = lmmax_rho * sp.nrmt
            mts.append(x[off : off + sz].reshape(lmmax_rho, sp.nrmt))
            off += sz
        mmts = None
        if nm:
            mmts = []
            for sp in ctx.species_of_atom:
                sz = lmmax_rho * sp.nrmt
                mmts.append(x[off : off + sz].reshape(lmmax_rho, sp.nrmt))
                off += sz
        return ig, mts, mig, mmts

    # FP mixing metric: real integration measures per packed coefficient —
    # interstitial plane-wave coefficients carry Omega, MT (lm, r) entries
    # carry the radial quadrature w_j r_j^2 (the reference mixes FP
    # Periodic_functions with their true inner products, mixer_functions.cpp
    # periodic_function_property; a plain l2 over the packed vector lets the
    # ~10^5 MT coefficients drown the interstitial ones and destabilizes
    # the Anderson geometry — Fe test19 loses its moment at beta = 0.5)
    _wig = np.full(2 * ctx.gvec.num_gvec, ctx.omega)
    _wmt = []
    for sp in ctx.species_of_atom:
        wr = radial_weights(sp.r) * sp.r**2
        _wmt.append(
            np.broadcast_to(wr, (num_lm(ctx.lmax_rho), sp.nrmt)).ravel()
        )
    _wparts = [_wig] + ([_wig] if nm else []) + _wmt + (_wmt if nm else [])
    _w = np.concatenate(_wparts)
    mixer = Mixer(cfg.mixer, weight=_w, rms_weight=_w / ctx.omega)
    _fv_warm: dict = {}  # per-k warm-start vectors for the iterative solve
    n = np.prod(ctx.dims)
    etot_history, rms_history = [], []
    e = {}
    mu, entropy_sum, occ = 0.0, 0.0, None
    evals_k = None
    converged = False
    num_done = 0
    core_esum_tot = 0.0

    from sirius_tpu.utils.profiler import add_time, reset_timers, timer_report

    reset_timers()
    _t_mark = [time.perf_counter()]

    def _lap(name):
        now = time.perf_counter()
        add_time(name, now - _t_mark[0])
        _t_mark[0] = now

    for it in range(p.num_dft_iter):
        _t_mark[0] = time.perf_counter()
        # ---- potential from current density ----
        # Hartree: Weinert pseudocharge
        qmt = []
        for ia in range(nat):
            sp = ctx.species_of_atom[ia]
            q = mt_multipoles(rho_mt[ia], sp.r)
            q[0] += -sp.zn * Y00  # nuclear point charge
            qmt.append(q)
        qpw = [
            pw_sphere_multipoles(
                rho_ig, ctx.gvec.millers, ctx.gvec.gcart, ctx.positions[ia],
                ctx.rmt[ia], ctx.lmax_pot,
            )
            for ia in range(nat)
        ]
        dq = [qmt[ia] - qpw[ia] for ia in range(nat)]
        rho_ps = pseudo_density_g(
            rho_ig, ctx.gvec.millers, ctx.gvec.gcart, ctx.omega, ctx.positions,
            ctx.rmt, dq, ctx.lmax_pot,
        )
        vh_ig = interstitial_potential_g(
            rho_ps, ctx.gvec.glen2,
            molecule_rcut=(0.5 * ctx.omega ** (1.0 / 3.0) if p.molecule else 0.0),
        )
        vh_mt, v_el_nuc = [], []
        for ia in range(nat):
            sp = ctx.species_of_atom[ia]
            vb = sphere_boundary_lm(
                vh_ig, ctx.gvec.millers, ctx.gvec.gcart, ctx.positions[ia],
                ctx.rmt[ia], ctx.lmax_pot,
            )
            v, v00 = mt_coulomb_potential(
                rho_mt[ia][:lmmax_pot], sp.r, sp.zn, vb
            )
            vh_mt.append(v)
            v_el_nuc.append(v00)

        # XC
        rho_r = ctx.g2r(rho_ig)
        bxc_r, bxc_mt = None, [None] * nat
        gbox = None
        if ctx.xc.is_gga:
            gbox = getattr(ctx, "_gbox", None)
            if gbox is None:
                gbox = ctx._gbox = gcart_box(ctx.dims, ctx.lattice)
        if nm:
            mag_r = ctx.g2r(mag_ig)
            vxc_r, exc_r, bxc_r = interstitial_xc(rho_r, ctx.xc, mag_r, gbox=gbox)
        else:
            vxc_r, exc_r = interstitial_xc(rho_r, ctx.xc, gbox=gbox)
        vxc_mt, exc_mt = [], []
        for ia in range(nat):
            v, ex, bx = mt_xc(
                rho_mt[ia], ctx.species_of_atom[ia].r, ctx.xc, ctx.sht,
                mag_lm=mag_mt[ia] if nm else None,
            )
            vxc_mt.append(v)
            exc_mt.append(ex)
            bxc_mt[ia] = bx

        # effective potential
        vh_r = ctx.g2r(vh_ig)
        veff_r = vh_r + vxc_r
        veff_mt = [vh_mt[ia] + vxc_mt[ia] for ia in range(nat)]

        _lap("fp::potential")
        # ---- radial basis at the current spherical potential ----
        basis_by_atom = []
        core_rho, core_esum, core_leak = [], 0.0, 0.0
        for ia in range(nat):
            sp = ctx.species_of_atom[ia]
            v_sph = veff_mt[ia][0] * Y00  # includes -Z/r
            basis_by_atom.append(
                build_radial_basis(sp, v_sph, ctx.lmax_apw, rel_val)
            )
            cr, ce, cl = core_states_density(sp, v_sph, rel_core)
            core_rho.append(cr)
            core_esum += ce
            core_leak += cl
        # ghost guard for the fv solve: nothing physical lies far below the
        # deepest RESOLVED linearization energy of the valence basis
        enu_all = [e for b in basis_by_atom for e in b.enu] + [
            e for b in basis_by_atom for e in b.lo_enu
        ]
        e_floor_fv = min(enu_all) - 5.0
        core_esum_tot = core_esum

        _lap("fp::radial_core")
        # ---- band problem per k: first variation (no B field) ----
        # iterative (matrix-free) fv solve when the deck asks for davidson
        # (reference diagonalize_fp.hpp:271); dense exact is the default
        # and the verification fallback. IORA's overlap correction is not
        # in the matrix-free operator yet — keep dense there.
        use_iter = (
            cfg.iterative_solver.type == "davidson" and rel_val != "iora"
        )
        # ZORA/IORA interstitial mass correction: the kinetic convolution
        # uses theta/M with M = 1 - (alpha^2/2) V(r) (reference
        # generate_pw_coefs + set_fv_h_o_it); IORA also corrects O
        kin_box = o2_box = m_r = None
        if rel_val in ("zora", "iora"):
            from sirius_tpu.lapw.radial_solver import SQ_ALPHA_HALF

            m_r = 1.0 - SQ_ALPHA_HALF * veff_r
        th_box = vth_box = None
        if not use_iter:
            th_box = np.fft.fftn(ctx.theta_r) / n
            vth_box = np.fft.fftn(veff_r * ctx.theta_r) / n
            if m_r is not None:
                kin_box = np.fft.fftn(ctx.theta_r / m_r) / n
                if rel_val == "iora":
                    o2_box = SQ_ALPHA_HALF * np.fft.fftn(ctx.theta_r / m_r**2) / n
        evals_k, C_k = [], []
        for ik, k in enumerate(ctx.kpoints):
            if use_iter:
                from sirius_tpu.lapw.fv_iter import build_fv_params, davidson_fv

                kin_r = (
                    ctx.theta_r / m_r if rel_val == "zora" else None
                )
                fvp = build_fv_params(
                    ctx.gkmill[ik], k, ctx.lattice, ctx.positions, ctx.rmt,
                    basis_by_atom,
                    [v[:lmmax_pot] for v in veff_mt],
                    ctx.theta_r, veff_r, kin_r, ctx.dims, ctx.omega,
                )
                import jax.numpy as _jnp

                x0 = _fv_warm.get(ik)
                ev, X, _rn = davidson_fv(
                    fvp, nev,
                    num_steps=cfg.iterative_solver.num_steps,
                    res_tol=cfg.iterative_solver.residual_tolerance,
                    x0=None if x0 is None else _jnp.asarray(x0),
                )
                ev = np.asarray(ev)
                C = np.asarray(X).T
                _fv_warm[ik] = np.asarray(X)
                # ghost guard: the dense path filters near-null overlap
                # directions against e_floor (diagonalize_fv); the
                # iterative subspace can still converge onto such a ghost
                # — fall back to the exact solve for this k if any
                # eigenvalue dives below the plausible floor
                if e_floor_fv is not None and np.any(ev < e_floor_fv):
                    H, O = assemble_fv(
                        ctx.gkmill[ik], k, ctx.lattice, ctx.positions,
                        ctx.rmt, basis_by_atom,
                        [v[:lmmax_pot] for v in veff_mt],
                        np.fft.fftn(ctx.theta_r) / n,
                        np.fft.fftn(veff_r * ctx.theta_r) / n,
                        ctx.dims, ctx.omega,
                        kin_box=None if m_r is None
                        else np.fft.fftn(ctx.theta_r / m_r) / n,
                    )
                    ev, C = diagonalize_fv(H, O, nev, e_floor=e_floor_fv)
                    _fv_warm.pop(ik, None)  # do not re-seed the ghost
            else:
                H, O = assemble_fv(
                    ctx.gkmill[ik], k, ctx.lattice, ctx.positions, ctx.rmt,
                    basis_by_atom,
                    [v[:lmmax_pot] for v in veff_mt],
                    th_box, vth_box, ctx.dims, ctx.omega,
                    kin_box=kin_box, o2_box=o2_box,
                )
                ev, C = diagonalize_fv(H, O, nev, e_floor=e_floor_fv)
            evals_k.append(ev)
            C_k.append(C)

        # MT expansion coefficients per (k, atom) — shared by the second
        # variation and the density build
        lo_index = []
        for ja in range(nat):
            for ilo, lof in enumerate(basis_by_atom[ja].lo):
                for m in range(-lof.l, lof.l + 1):
                    lo_index.append((ja, ilo, lof.l, m))
        gk_cart_k = [
            (ctx.gkmill[ik] + k) @ ctx.recip
            for ik, k in enumerate(ctx.kpoints)
        ]
        mtix = [mt_index(basis_by_atom[ia], ctx.lmax_apw) for ia in range(nat)]
        W_k = []
        for ik, k in enumerate(ctx.kpoints):
            Ws = []
            for ia in range(nat):
                A, B = matching_coefficients(
                    gk_cart_k[ik], ctx.positions[ia], ctx.gkmill[ik], k,
                    ctx.rmt[ia], basis_by_atom[ia], ctx.omega,
                )
                cols = atom_lo_cols(lo_index, ia, len(ctx.gkmill[ik]))
                Ws.append(
                    mt_expansion_coeffs(
                        C_k[ik], A, B, cols, basis_by_atom[ia], ctx.lmax_apw
                    )
                )
            W_k.append(Ws)

        _lap("fp::fv_solve")
        # ---- second variation: diagonal fv energies + sigma_z B coupling
        # (reference diagonalize_fp.hpp second-variational branch) ----
        if nm:
            from sirius_tpu.lapw.fv import gaunt_hybrid as _gh

            BMT = []
            for ia in range(nat):
                b = basis_by_atom[ia]
                rf, lm_of, rf_of = mtix[ia]
                gh = _gh(ctx.lmax_apw, ctx.lmax_pot, ctx.lmax_apw)
                wr2 = radial_weights(b.r) * b.r * b.r
                F = np.stack(rf)
                RI = np.einsum(
                    "ax,Lx,bx,x->abL", F, bxc_mt[ia][: num_lm(ctx.lmax_pot)],
                    F, wr2, optimize=True,
                )
                GG = gh[lm_of[:, None], :, lm_of[None, :]]
                BMT.append(
                    np.einsum(
                        "pqL,pqL->pq", GG,
                        RI[rf_of[:, None], rf_of[None, :], :],
                    )
                )
            bth_r = bxc_r * ctx.theta_r
            evals_sv, U_k = [], []
            for ik in range(len(ctx.kpoints)):
                ngk = len(ctx.gkmill[ik])
                i0 = np.mod(ctx.gkmill[ik][:, 0], ctx.dims[0])
                i1 = np.mod(ctx.gkmill[ik][:, 1], ctx.dims[1])
                i2 = np.mod(ctx.gkmill[ik][:, 2], ctx.dims[2])
                PSI = np.zeros((nev, n), dtype=np.complex128)
                for ib in range(nev):
                    box = np.zeros(ctx.dims, dtype=np.complex128)
                    box[i0, i1, i2] = C_k[ik][:ngk, ib]
                    PSI[ib] = (np.fft.ifftn(box) * n / np.sqrt(ctx.omega)).ravel()
                Bij = (ctx.omega / n) * (
                    np.conj(PSI) @ (bth_r.ravel()[:, None] * PSI.T)
                )
                for ia in range(nat):
                    W = W_k[ik][ia]
                    Bij += W.conj().T @ BMT[ia] @ W
                Bij = 0.5 * (Bij + Bij.conj().T)
                evs, Us = [], []
                for s in (+1, -1):
                    hsv = np.diag(evals_k[ik]) + s * Bij
                    ev_s, u_s = np.linalg.eigh(hsv)
                    evs.append(ev_s)
                    Us.append(u_s)
                evals_sv.append(np.stack(evs))  # [2, nev]
                U_k.append(Us)
            evals = np.asarray(evals_sv)  # [nk, 2, nev]
        else:
            evals = np.asarray(evals_k)[:, None, :]  # [nk, 1, nev]
            U_k = [[np.eye(nev, dtype=np.complex128)] for _ in ctx.kpoints]

        mu, occ, entropy_sum = find_fermi(
            evals, np.asarray(ctx.kweights), float(ctx.num_valence),
            p.smearing_width, kind=p.smearing,
            max_occupancy=(2.0 if ns == 1 else 1.0),
        )
        occ_np = np.asarray(occ)  # [nk, ns, nev]

        _lap("fp::sv_occupancy")
        # ---- new density (per spin channel) ----
        rho_mt_new, mag_mt_new = [], []
        for ia in range(nat):
            b = basis_by_atom[ia]
            rf, lm_of, rf_of = mtix[ia]
            nidx = len(lm_of)
            D_s = np.zeros((ns, nidx, nidx), dtype=np.complex128)
            for ik in range(len(ctx.kpoints)):
                W = W_k[ik][ia]
                for ispn in range(ns):
                    Wsv = W @ U_k[ik][ispn]
                    wocc = ctx.kweights[ik] * occ_np[ik, ispn]
                    D_s[ispn] += (np.conj(Wsv) * wocc[None, :]) @ Wsv.T
            rho = mt_density_from_dm(
                D_s.sum(axis=0), lm_of, rf_of, rf, ctx.lmax_rho, ctx.lmax_apw
            )
            rho[0] += core_rho[ia] / Y00
            rho_mt_new.append(rho)
            if nm:
                mag_mt_new.append(
                    mt_density_from_dm(
                        D_s[0] - D_s[1], lm_of, rf_of, rf, ctx.lmax_rho,
                        ctx.lmax_apw,
                    )
                )
        if nm:
            spin_rho = []
            for ispn in range(ns):
                C_sv = [
                    C_k[ik][: len(ctx.gkmill[ik])] @ U_k[ik][ispn]
                    for ik in range(len(ctx.kpoints))
                ]
                spin_rho.append(
                    interstitial_density_box(
                        C_sv, ctx.gkmill, occ_np[:, ispn, :], ctx.kweights,
                        ctx.dims, ctx.omega,
                    )
                )
            rho_r_new = spin_rho[0] + spin_rho[1]
            mag_r_new = spin_rho[0] - spin_rho[1]
        else:
            rho_r_new = interstitial_density_box(
                C_k, ctx.gkmill, occ_np[:, 0, :], ctx.kweights, ctx.dims,
                ctx.omega,
            )
        # Core spill-out is NOT compensated during the SCF: the reference
        # adds the core density only inside the MT (density.cpp:1112-1121)
        # and renormalizes the initial density only (normalize() called
        # from initial_density alone) — the leaked charge is simply absent
        # from the SCF density. Spreading it as a uniform interstitial
        # background (our previous behavior) shifts the Hartree potential
        # by a near-uniform delta that leaks into every energy term via
        # the core states (the test19-class uniform MT offset).
        rho_ig_new = np.fft.fftn(rho_r_new).ravel()[ctx.gvec.fft_index] / n
        if nm:
            mag_ig_new = np.fft.fftn(mag_r_new).ravel()[ctx.gvec.fft_index] / n

        # IBZ k-sums require the space-group projection of the density
        # (reference symmetrize_field4d after generate_valence)
        if ctx.sym is not None and len(ctx.sym.ops) > 1:
            from sirius_tpu.lapw.symmetrize_fp import (
                symmetrize_mt,
                symmetrize_pw_fp,
            )

            rho_ig_new = symmetrize_pw_fp(
                rho_ig_new, ctx.sym.ops, ctx.gvec.millers
            )
            rho_mt_new = symmetrize_mt(rho_mt_new, ctx.sym.ops, ctx.lmax_rho)
            rho_r_new = ctx.g2r(rho_ig_new)
            if nm:
                # collinear m_z is the z-component of an axial vector: each
                # op carries spin_sign = det(R) R_zz (sublattice-swap ops
                # are -1; without the sign AFM fields average to zero)
                mag_ig_new = symmetrize_pw_fp(
                    mag_ig_new, ctx.sym.ops, ctx.gvec.millers, axial_z=True
                )
                mag_mt_new = symmetrize_mt(
                    mag_mt_new, ctx.sym.ops, ctx.lmax_rho, axial_z=True
                )
                mag_r_new = ctx.g2r(mag_ig_new)

        sq4pi_ = np.sqrt(4.0 * np.pi)
        mt_charge = sum(
            sq4pi_ * float(rint(rho_mt_new[ia][0] * ctx.species_of_atom[ia].r ** 2,
                                ctx.species_of_atom[ia].r))
            for ia in range(nat)
        )
        istl_charge = ctx.istl_integral(rho_r_new, np.ones(ctx.dims))
        total_charge = mt_charge + istl_charge

        _lap("fp::density")
        # ---- energies (at the INPUT potential, OUTPUT density) ----
        eval_sum = float(
            np.sum(
                np.asarray(ctx.kweights)[:, None, None] * occ_np
                * np.asarray(evals)
            )
        ) + core_esum
        rho_mt_tot = rho_mt_new
        e_veff = ctx.mt_integral(rho_mt_tot, veff_mt) + ctx.istl_integral(
            rho_r_new, veff_r
        )
        e_vha = ctx.mt_integral(rho_mt_tot, vh_mt) + ctx.istl_integral(
            rho_r_new, vh_r
        )
        e_vxc = ctx.mt_integral(rho_mt_tot, vxc_mt) + ctx.istl_integral(
            rho_r_new, vxc_r
        )
        sq4pi = np.sqrt(4.0 * np.pi)
        e_exc = sum(
            float(rint(exc_mt[ia][0] * ctx.species_of_atom[ia].r ** 2,
                               ctx.species_of_atom[ia].r)) * sq4pi
            for ia in range(nat)
        ) + ctx.istl_integral(exc_r, np.ones(ctx.dims))
        e_enuc = -0.5 * sum(
            ctx.species_of_atom[ia].zn * v_el_nuc[ia] for ia in range(nat)
        )
        e_bxc = 0.0
        if nm:
            e_bxc = ctx.mt_integral(mag_mt_new, bxc_mt) + ctx.istl_integral(
                mag_r_new, bxc_r
            )
        e_kin = eval_sum - e_veff - e_bxc
        e_total = e_kin + e_exc + 0.5 * e_vha + e_enuc
        e = {
            "total": e_total,
            "free": e_total + float(entropy_sum),
            "eval_sum": eval_sum,
            "core_eval_sum": core_esum,
            "kin": e_kin,
            "veff": e_veff,
            "vha": e_vha,
            "vxc": e_vxc,
            "exc": e_exc,
            "enuc": e_enuc,
            "ewald": 0.0,
            "bxc": e_bxc,
            "entropy_sum": float(entropy_sum),
            "scf_correction": 0.0,
        }
        etot_history.append(e_total)

        _lap("fp::energies")
        # ---- mix ----
        x_in = pack(rho_ig, rho_mt, mag_ig, mag_mt)
        x_out = pack(
            rho_ig_new, rho_mt_new,
            mag_ig_new if nm else None, mag_mt_new if nm else None,
        )
        rms = float(np.sqrt(np.mean(np.abs(x_out - x_in) ** 2)))
        rms_history.append(rms)
        num_done = it + 1
        de = (
            abs(etot_history[-1] - etot_history[-2])
            if len(etot_history) > 1
            else np.inf
        )
        if cfg.control.verbosity >= 2:
            print(
                f"[scf_fp] it={it + 1:3d} etot={e_total:+.10f} "
                f"rms={rms:.3e} de={de:.3e}",
                flush=True,
            )
        if cfg.control.verbosity >= 3:
            # pack layout: [rho_ig, mag_ig?] (as float views) then MT blocks
            nig = 2 * len(rho_ig) * (2 if nm else 1)
            d_ig = x_out[:nig] - x_in[:nig]
            d_mt = x_out[nig:] - x_in[nig:]
            print(
                f"[scf_fp]   rms_ig={float(np.sqrt(np.mean(np.abs(d_ig)**2))):.3e}"
                f" rms_mt={float(np.sqrt(np.mean(np.abs(d_mt)**2))):.3e}",
                flush=True,
            )
        if rms < p.density_tol and de < p.energy_tol:
            converged = True
            rho_ig, rho_mt = rho_ig_new, rho_mt_new
            if nm:
                mag_ig, mag_mt = mag_ig_new, mag_mt_new
            break
        x_mix = mixer.mix(x_in, x_out)
        rho_ig, rho_mt, mag_ig, mag_mt = unpack(x_mix)
        _lap("fp::mix")

    band_gap = 0.0
    ev_flat = np.asarray(evals)
    o_flat = occ_np
    maxocc = 2.0 if ns == 1 else 1.0
    filled = ev_flat[o_flat > 1e-8 * maxocc]
    empty = ev_flat[o_flat <= 1e-8 * maxocc]
    if len(empty) and len(filled):
        band_gap = max(0.0, float(empty.min() - filled.max()))

    mag_result = None
    if nm:
        mt_moments = [
            float(
                np.sqrt(4.0 * np.pi)
                * rint(mag_mt[ia][0] * ctx.species_of_atom[ia].r ** 2,
                       ctx.species_of_atom[ia].r)
            )
            for ia in range(nat)
        ]
        mr = ctx.g2r(mag_ig)
        m_tot = sum(mt_moments) + ctx.istl_integral(mr, np.ones(ctx.dims))
        mag_result = {
            "total": [0.0, 0.0, m_tot],
            "atoms": [[0.0, 0.0, m] for m in mt_moments],
        }

    return {
        "converged": converged,
        "num_scf_iterations": num_done,
        "efermi": float(mu),
        "band_gap": band_gap,
        "rho_min": 0.0,
        "etot_history": etot_history,
        "rms_history": rms_history,
        "scf_time": time.time() - t0,
        "energy": e,
        "mt_charge": mt_charge,
        "interstitial_charge": istl_charge,
        "total_charge": total_charge,
        "core_leakage": core_leak,
        "band_energies": np.asarray(evals).tolist(),
        "band_occupancies": occ_np.tolist(),
        "counters": {},
        "timers": timer_report(),
        **({"magnetisation": mag_result} if mag_result else {}),
    }


def run_scf_fp_from_file(path: str, base_dir: str | None = None) -> dict:
    import os

    cfg = load_config(path)
    if base_dir is None:
        base_dir = os.path.dirname(os.path.abspath(path))
    return run_scf_fp(cfg, base_dir)
