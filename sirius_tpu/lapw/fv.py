"""First-variational LAPW Hamiltonian/overlap assembly and diagonalization.

Reference: src/hamiltonian/diagonalize_fp.hpp:29 (fv exact setup),
set_fv_h_o in hamiltonian.hpp. Matrix structure over the basis
[APW(G) ... | lo ...]:

  O_GG' = Theta(G-G') + sum_a sum_lm A*(G) A(G') + N_l B*(G) B(G')
  H_GG' = (1/2)(G+k).(G'+k) Theta(G-G') + (V_eff Theta)(G-G')
          + sum_a sum_lm,l'm' [APW radial x Gaunt x V_lm integrals]

with the spherical part through the (f, hf) overlap trick (basis.py) and
the non-spherical part via hybrid Gaunt coefficients
<Y_l1m1|R_l3m3|Y_l2m2> (the reference's SHT::gaunt_hybrid).

The interstitial convolutions Theta(G-G') and (V Theta)(G-G') are read
from FFT boxes of the fine G set."""

from __future__ import annotations

from functools import lru_cache

import numpy as np

from sirius_tpu.core.sht import _sphere_quadrature, lm_index, num_lm, ylm_complex, ylm_real


@lru_cache(maxsize=4)
def gaunt_hybrid(lmax1: int, lmax3: int, lmax2: int) -> np.ndarray:
    """G[lm1, lm3, lm2] = int conj(Y_l1m1) R_l3m3 Y_l2m2 dOmega via exact
    quadrature (complex result; reference SHT::gaunt_hybrid)."""
    deg = lmax1 + lmax2 + lmax3 + 2
    pts, w = _sphere_quadrature(deg)
    y1 = ylm_complex(lmax1, pts)
    r3 = ylm_real(lmax3, pts)
    y2 = ylm_complex(lmax2, pts)
    return np.einsum("pa,pb,pc,p->abc", np.conj(y1), r3, y2, w, optimize=True)


def interstitial_tables(theta_g, veff_g, fft_index, dims):
    """Real-space boxes of Theta and V*Theta for difference-vector lookups.

    Returns (theta_box_g, vtheta_box_g): FFT boxes in G layout whose entry
    at the FFT index of (G - G') gives the convolution coefficient."""
    import jax.numpy as jnp

    from sirius_tpu.core.fftgrid import g_to_r, r_to_g

    th_r = np.asarray(g_to_r(jnp.asarray(theta_g), jnp.asarray(fft_index), dims)).real
    v_r = np.asarray(g_to_r(jnp.asarray(veff_g), jnp.asarray(fft_index), dims)).real
    n = dims[0] * dims[1] * dims[2]
    th_box = np.fft.fftn(th_r) / n
    vth_box = np.fft.fftn(v_r * th_r) / n
    return th_box, vth_box


def _box_lookup(box, mill_diff, dims):
    """box values at miller-index differences [n, n, 3] -> [n, n]."""
    i0 = np.mod(mill_diff[..., 0], dims[0])
    i1 = np.mod(mill_diff[..., 1], dims[1])
    i2 = np.mod(mill_diff[..., 2], dims[2])
    return box[i0, i1, i2]


def assemble_fv(gk_millers, k_frac, lattice, positions, rmt_by_atom,
                basis_by_atom, v_mt_lm_by_atom, theta_box, vtheta_box,
                dims, omega, kin_box=None, o2_box=None):
    """(H, O) complex Hermitian matrices over [APW(G) | lo] for one k.

    gk_millers: [nG, 3] integer G of the APW set; v_mt_lm_by_atom: per
    atom [lmmax_pot, nr] REAL-harmonic non-spherical potential (the
    spherical lm=0 component must be EXCLUDED — it lives in the radial
    basis through hf).

    kin_box: convolution box for the kinetic term. Plain theta for
    rel=none; FFT(theta/M) for ZORA/IORA (the interstitial mass correction,
    reference set_fv_h_o_it + generate_pw_coefs). o2_box: IORA's overlap
    correction box FFT(theta/M^2) scaled by alpha^2/2 at the caller."""
    # rows of recip are b_i (a_i . b_j = 2 pi delta_ij): gcart = m @ recip,
    # NOT m @ recip.T (equal only for symmetric lattice matrices)
    recip = 2.0 * np.pi * np.linalg.inv(lattice).T
    gk_cart = (gk_millers + k_frac) @ recip
    ng = len(gk_millers)
    nat = len(positions)
    # lo layout
    lo_index = []  # (ia, ilo, l, m) -> column
    for ia in range(nat):
        for ilo, lof in enumerate(basis_by_atom[ia].lo):
            for m in range(-lof.l, lof.l + 1):
                lo_index.append((ia, ilo, lof.l, m))
    nlo = len(lo_index)
    ntot = ng + nlo
    H = np.zeros((ntot, ntot), dtype=np.complex128)
    O = np.zeros((ntot, ntot), dtype=np.complex128)

    # --- interstitial (APW-APW) ---
    md = gk_millers[:, None, :] - gk_millers[None, :, :]
    th = _box_lookup(theta_box, md, dims)
    vth = _box_lookup(vtheta_box, md, dims)
    kin = th if kin_box is None else _box_lookup(kin_box, md, dims)
    tfac = 0.5 * np.einsum("gi,hi->gh", gk_cart, gk_cart)
    O[:ng, :ng] = th
    if o2_box is not None:  # IORA: O += (alpha^2/2) T (theta/M^2)
        O[:ng, :ng] += tfac * _box_lookup(o2_box, md, dims)
    H[:ng, :ng] = tfac * kin + vth

    from sirius_tpu.lapw.basis import matching_coefficients

    for ia in range(nat):
        b = basis_by_atom[ia]
        r = b.r
        lmax = b.lmax_apw
        lmmax = num_lm(lmax)
        A, B = matching_coefficients(
            gk_cart, positions[ia], gk_millers, k_frac, rmt_by_atom[ia],
            b, omega,
        )
        # per-l 2x2 radial overlap and spherical-H blocks
        ov = np.zeros((lmax + 1, 2, 2))
        hs = np.zeros((lmax + 1, 2, 2))
        for l in range(lmax + 1):
            for i, fi in enumerate(b.aw[l]):
                for jj, fj in enumerate(b.aw[l]):
                    ov[l, i, jj] = b.overlap(fi, fj)
                    hs[l, i, jj] = b.h_sph(fi, fj)
        l_of_lm = np.concatenate([[l] * (2 * l + 1) for l in range(lmax + 1)])
        ovl = ov[l_of_lm]  # [lmmax, 2, 2]
        hsl = hs[l_of_lm]
        C = np.stack([A, B], axis=2)  # [nG, lmmax, 2]
        O[:ng, :ng] += np.einsum(
            "gmi,mij,hmj->gh", np.conj(C), ovl, C, optimize=True
        )
        H[:ng, :ng] += np.einsum(
            "gmi,mij,hmj->gh", np.conj(C), hsl, C, optimize=True
        )
        # --- non-spherical MT potential over the FULL MT index (APW + lo):
        # the generic sandwich conj(W) V W^T with W mapping basis columns to
        # MT expansion entries — lo rows/columns get the same V_nonsph
        # coupling as the APW block (reference set_fv_h_o lo contributions)
        v_lm = v_mt_lm_by_atom[ia]
        if v_lm is not None and np.abs(v_lm[1:]).max() > 1e-14:
            from sirius_tpu.lapw.density_fp import mt_index
            from sirius_tpu.lapw.quad import radial_weights

            lmax_pot = int(np.sqrt(v_lm.shape[0])) - 1
            gh = gaunt_hybrid(lmax, lmax_pot, lmax)  # [lm1, lm3, lm2]
            rf, lm_of, rf_of = mt_index(b, lmax)
            nidx = len(lm_of)
            wr2 = radial_weights(r) * r * r
            F = np.stack(rf)  # [nrf, nr]
            RI = np.einsum("ax,Lx,bx,x->abL", F, v_lm, F, wr2, optimize=True)
            RI[:, :, 0] = 0.0  # spherical part lives in h_sph already
            GG = gh[lm_of[:, None], :, lm_of[None, :]]  # [p, q, lm3]
            V = np.einsum(
                "pqL,pqL->pq", GG, RI[rf_of[:, None], rf_of[None, :], :]
            )
            W = np.zeros((ntot, nidx), dtype=np.complex128)
            W[:ng, 0 : 2 * lmmax : 2] = A
            W[:ng, 1 : 2 * lmmax : 2] = B
            kk = 2 * lmmax
            for col, (ja, _, _, _) in enumerate(lo_index):
                if ja == ia:
                    W[ng + col, kk] = 1.0
                    kk += 1
            H += np.einsum("xp,pq,yq->xy", np.conj(W), V, W, optimize=True)
        # --- lo blocks ---
        for col, (ja, ilo, l, m) in enumerate(lo_index):
            if ja != ia:
                continue
            j = ng + col
            lof = b.lo[ilo]
            lm = lm_index(l, m)
            ou = b.overlap(b.aw[l][0], lof)
            od = b.overlap(b.aw[l][1], lof)
            hu = b.h_sph(b.aw[l][0], lof)
            hd = b.h_sph(b.aw[l][1], lof)
            O[:ng, j] += np.conj(A[:, lm]) * ou + np.conj(B[:, lm]) * od
            H[:ng, j] += np.conj(A[:, lm]) * hu + np.conj(B[:, lm]) * hd
            O[j, :ng] = np.conj(O[:ng, j])
            H[j, :ng] = np.conj(H[:ng, j])
            for col2, (ja2, ilo2, l2, m2) in enumerate(lo_index):
                if ja2 != ia or l2 != l or m2 != m:
                    continue
                j2 = ng + col2
                lof2 = b.lo[ilo2]
                O[j, j2] += b.overlap(lof, lof2)
                H[j, j2] += b.h_sph(lof, lof2)
    H = 0.5 * (H + H.conj().T)
    O = 0.5 * (O + O.conj().T)
    return H, O


def _filtered_solve(H, O, nev, s, u, good):
    t = u[:, good] * (1.0 / np.sqrt(s[good]))[None, :]
    a = t.conj().T @ H @ t
    a = 0.5 * (a + a.conj().T)
    e, c = np.linalg.eigh(a)
    if len(e) < nev:
        # fewer good overlap directions than requested bands: pad with a
        # large FINITE sentinel (inf would NaN-poison the Fermi bisection
        # and the second-variation eigh) / zero vectors so every k returns
        # exactly nev bands; far above mu, so occupation is a true zero
        pad = nev - len(e)
        sentinel = (e.max() if len(e) else 0.0) + 1e3
        e = np.concatenate([e, np.full(pad, sentinel)])
        c = np.pad(c, ((0, 0), (0, pad)))
    v = t @ c[:, :nev]
    return e[:nev], v


def diagonalize_fv(H, O, nev: int, e_floor: float | None = None):
    """Lowest nev of the generalized problem. LAPACK's subset driver
    (Cholesky + syevr) is ~6x faster than a full eigh at LAPW sizes when
    nev << n.

    e_floor: ghost guard. A near-null O direction amplifies the MT
    quadrature noise of H by |c|^2 / (x^H O x) and can surface as a
    spurious DEEP state (classic lo+APW linear-dependence ghost; Fe test19
    had one at -16.5 Ha from an O eigenvalue at 1.6e-4 relative — the
    reference's davidson path removes such components via
    get_singular_components, diagonalize_fp.hpp:238). When the computed
    spectrum dips below e_floor, the smallest O components are dropped one
    at a time until it recovers; a FIXED relative threshold is wrong (He
    molecule boxes legitimately carry small O components)."""
    nev = min(nev, H.shape[0])
    try:
        from scipy.linalg import eigh as seigh

        L = np.linalg.cholesky(O)
        d = np.real(np.diag(L))
        if d.min() < 1e-7 * d.max():
            raise np.linalg.LinAlgError("overlap nearly singular")
        e, v = seigh(H, O, subset_by_index=[0, nev - 1])
        if e_floor is None or e[0] > e_floor:
            return e, v
    except (ImportError, ValueError, np.linalg.LinAlgError):
        pass
    s, u = np.linalg.eigh(O)
    order = np.argsort(s)
    good = s > 1e-9 * s.max()
    e, v = _filtered_solve(H, O, nev, s, u, good)
    if e_floor is not None:
        for i in range(12):
            if not np.any(good) or len(e) == 0 or e[0] > e_floor:
                break
            # drop the smallest surviving O component
            for idx in order:
                if good[idx]:
                    good[idx] = False
                    break
            e, v = _filtered_solve(H, O, nev, s, u, good)
    return e, v
