"""Full-potential XC: muffin-tin angular-grid evaluation + interstitial.

Reference: src/potential/xc_mt.cpp (density -> (r, Omega) grid via SHT,
pointwise libxc, back-projection of v_xc/e_xc onto R_lm) and xc.cpp for the
interstitial FFT-grid branch. Here both reuse the autodiff XCFunctional.

GGA in the muffin-tin needs grad rho on the angular grid:
  grad rho = sum_lm [ drho_lm/dr R_lm r-hat + (rho_lm/r) r grad_ang R_lm ]
and sigma = |grad rho|^2. The angular gradient of R_lm is evaluated by
finite rotation-free differentiation of the real harmonics on the
quadrature grid via the exact identity
  grad = r-hat d/dr + (1/r) grad_S,
with grad_S R_lm computed from the gradient formula for complex Ylm
re-expressed in the real basis (here: numerical tangent-plane derivative,
exact for band-limited functions on the dense product quadrature).
"""

from __future__ import annotations

import numpy as np

from sirius_tpu.core.sht import num_lm, ylm_real


class MtSht:
    """Forward/backward spherical-harmonic transform on a product
    quadrature exact through polynomial degree 2*lmax_eval."""

    def __init__(self, lmax_rho: int, lmax_pot: int, degree: int | None = None):
        from sirius_tpu.core.sht import _sphere_quadrature

        self.lmax_rho = lmax_rho
        self.lmax_pot = lmax_pot
        deg = degree if degree is not None else 2 * max(lmax_rho, lmax_pot) + 2
        self.pts, self.w = _sphere_quadrature(deg)
        self.rlm_rho = ylm_real(lmax_rho, self.pts)  # [np, lmmax_rho]
        self.rlm_pot = ylm_real(lmax_pot, self.pts)

    def to_grid(self, f_lm: np.ndarray) -> np.ndarray:
        """[lmmax, nr] -> [np, nr] values on the angular x radial grid."""
        return self.rlm_rho[:, : f_lm.shape[0]] @ f_lm

    def to_lm(self, f_pt: np.ndarray) -> np.ndarray:
        """[np, nr] -> [lmmax_pot, nr] real-harmonic projection."""
        return (self.rlm_pot * self.w[:, None]).T @ f_pt


def mt_xc(rho_lm, r, xc, sht: MtSht, mag_lm=None):
    """(vxc_lm [lmmax_pot, nr], exc_lm [lmmax_pot, nr], bxc_lm | None).

    LDA-level muffin-tin XC (the FP decks wired so far are LDA; the GGA
    extension adds sigma terms on the same grid). Collinear magnetism via
    mag_lm (z-component in real harmonics)."""
    import jax.numpy as jnp

    if xc.is_gga:
        raise NotImplementedError(
            "FP-LAPW muffin-tin XC is LDA-only so far; GGA needs the MT "
            "density gradient (reference xc_mt.cpp GGA branch)"
        )

    rho_pt = np.maximum(sht.to_grid(rho_lm), 1e-12)  # [np, nr]
    if mag_lm is None:
        res = xc.evaluate(jnp.asarray(rho_pt.ravel()))
        v = np.asarray(res["v"]).reshape(rho_pt.shape)
        e = np.asarray(res["e"]).reshape(rho_pt.shape)  # energy per volume
        return sht.to_lm(v), sht.to_lm(e), None
    m_pt = sht.to_grid(mag_lm)
    m_pt = np.clip(m_pt, -rho_pt + 1e-12, rho_pt - 1e-12)
    up = 0.5 * (rho_pt + m_pt).ravel()
    dn = 0.5 * (rho_pt - m_pt).ravel()
    res = xc.evaluate_polarized(jnp.asarray(up), jnp.asarray(dn))
    vu = np.asarray(res["v_up"]).reshape(rho_pt.shape)
    vd = np.asarray(res["v_dn"]).reshape(rho_pt.shape)
    e = np.asarray(res["e"]).reshape(rho_pt.shape)
    return (
        sht.to_lm(0.5 * (vu + vd)),
        sht.to_lm(e),
        sht.to_lm(0.5 * (vu - vd)),
    )


def interstitial_xc(rho_r, xc, mag_r=None):
    """(vxc_r, exc_density_r[, bxc_r]) pointwise on the FFT grid (full
    cell; the integrals later weight by the step function). Collinear
    magnetism via mag_r (z-component)."""
    import jax.numpy as jnp

    shape = rho_r.shape
    rho = np.maximum(rho_r, 1e-12)
    if mag_r is None:
        res = xc.evaluate(jnp.asarray(rho.ravel()))
        v = np.asarray(res["v"]).reshape(shape)
        e = np.asarray(res["e"]).reshape(shape)
        return v, e
    m = np.clip(mag_r, -rho + 1e-12, rho - 1e-12)
    res = xc.evaluate_polarized(
        jnp.asarray((0.5 * (rho + m)).ravel()), jnp.asarray((0.5 * (rho - m)).ravel())
    )
    vu = np.asarray(res["v_up"]).reshape(shape)
    vd = np.asarray(res["v_dn"]).reshape(shape)
    e = np.asarray(res["e"]).reshape(shape)
    return 0.5 * (vu + vd), e, 0.5 * (vu - vd)
