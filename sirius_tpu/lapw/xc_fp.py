"""Full-potential XC: muffin-tin angular-grid evaluation + interstitial.

Reference: src/potential/xc_mt.cpp (density -> (r, Omega) grid via SHT,
pointwise libxc, back-projection of v_xc/e_xc onto R_lm) and xc.cpp for the
interstitial FFT-grid branch. Here both reuse the autodiff XCFunctional.

GGA in the muffin-tin needs grad rho on the angular grid:
  grad rho = sum_lm [ drho_lm/dr R_lm r-hat + (rho_lm/r) r grad_ang R_lm ]
and sigma = |grad rho|^2. The angular gradient of R_lm is evaluated by
finite rotation-free differentiation of the real harmonics on the
quadrature grid via the exact identity
  grad = r-hat d/dr + (1/r) grad_S,
with grad_S R_lm computed from the gradient formula for complex Ylm
re-expressed in the real basis (here: numerical tangent-plane derivative,
exact for band-limited functions on the dense product quadrature).
"""

from __future__ import annotations

import numpy as np

from sirius_tpu.core.sht import num_lm, ylm_real


class MtSht:
    """Forward/backward spherical-harmonic transform on a product
    quadrature exact through polynomial degree 2*lmax_eval."""

    def __init__(self, lmax_rho: int, lmax_pot: int, degree: int | None = None):
        from sirius_tpu.core.sht import _sphere_quadrature

        self.lmax_rho = lmax_rho
        self.lmax_pot = lmax_pot
        deg = degree if degree is not None else 2 * max(lmax_rho, lmax_pot) + 2
        self.pts, self.w = _sphere_quadrature(deg)
        self.rlm_rho = ylm_real(lmax_rho, self.pts)  # [np, lmmax_rho]
        self.rlm_pot = ylm_real(lmax_pot, self.pts)

    def to_grid(self, f_lm: np.ndarray) -> np.ndarray:
        """[lmmax, nr] -> [np, nr] values on the angular x radial grid."""
        return self.rlm_rho[:, : f_lm.shape[0]] @ f_lm

    def to_lm(self, f_pt: np.ndarray) -> np.ndarray:
        """[np, nr] -> [lmmax_pot, nr] real-harmonic projection."""
        return (self.rlm_pot * self.w[:, None]).T @ f_pt

    def to_lm_rho(self, f_pt: np.ndarray) -> np.ndarray:
        """[np, nr] -> [lmmax_rho, nr] projection (GGA gradient fields)."""
        return (self.rlm_rho * self.w[:, None]).T @ f_pt


def mt_xc_gga(rho_lm, r, xc, sht: MtSht, mag_lm=None):
    """GGA muffin-tin XC (reference xc_mt.cpp GGA branch): spectral
    cartesian gradients (dft/mt_gradient, spheric_function.hpp:559) of the
    channel densities, sigma on the angular grid, and the -div(vsigma
    grad n) potential term assembled spectrally and re-evaluated on the
    same quadrature — the identical scheme validated on the PAW on-site
    densities (dft/paw.xc_onsite_gga)."""
    import jax.numpy as jnp

    from sirius_tpu.dft.mt_gradient import divergence_lm_real, gradient_lm_real

    nlm = rho_lm.shape[0]
    if mag_lm is None:
        up_lm = dn_lm = 0.5 * rho_lm
    else:
        m = mag_lm if mag_lm.shape[0] == nlm else np.pad(
            mag_lm, ((0, nlm - mag_lm.shape[0]), (0, 0))
        )
        up_lm = 0.5 * (rho_lm + m)
        dn_lm = 0.5 * (rho_lm - m)
    gu = gradient_lm_real(up_lm, r)
    gd = gu if mag_lm is None else gradient_lm_real(dn_lm, r)
    to_pt = sht.to_grid
    up = np.maximum(to_pt(up_lm), 1e-20)
    dn = np.maximum(to_pt(dn_lm), 1e-20)
    gu_pt = np.stack([to_pt(gu[i]) for i in range(3)])
    gd_pt = gu_pt if mag_lm is None else np.stack([to_pt(gd[i]) for i in range(3)])
    suu = np.sum(gu_pt**2, axis=0)
    sud = np.sum(gu_pt * gd_pt, axis=0)
    sdd = np.sum(gd_pt**2, axis=0)
    shape = up.shape
    out = xc.evaluate_polarized(
        jnp.asarray(up.ravel()), jnp.asarray(dn.ravel()),
        jnp.asarray(suu.ravel()), jnp.asarray(sud.ravel()),
        jnp.asarray(sdd.ravel()),
    )
    e = np.asarray(out["e"]).reshape(shape)
    vu = np.asarray(out["v_up"]).reshape(shape)
    vd = np.asarray(out["v_dn"]).reshape(shape)
    vsuu = np.asarray(out["vsigma_uu"]).reshape(shape)
    vsud = np.asarray(out["vsigma_ud"]).reshape(shape)
    vsdd = np.asarray(out["vsigma_dd"]).reshape(shape)
    # W_s = 2 vsigma_ss grad n_s + vsigma_ud grad n_other; v_s -= div W_s
    proj = lambda f: sht.to_lm_rho(f)
    wu_lm = np.stack([proj(2.0 * vsuu * gu_pt[i] + vsud * gd_pt[i]) for i in range(3)])
    wd_lm = np.stack([proj(2.0 * vsdd * gd_pt[i] + vsud * gu_pt[i]) for i in range(3)])
    vu = vu - to_pt(divergence_lm_real(wu_lm, r))
    vd = vd - to_pt(divergence_lm_real(wd_lm, r))
    if mag_lm is None:
        return sht.to_lm(0.5 * (vu + vd)), sht.to_lm(e), None
    return (
        sht.to_lm(0.5 * (vu + vd)),
        sht.to_lm(e),
        sht.to_lm(0.5 * (vu - vd)),
    )


def mt_xc(rho_lm, r, xc, sht: MtSht, mag_lm=None):
    """(vxc_lm [lmmax_pot, nr], exc_lm [lmmax_pot, nr], bxc_lm | None).

    Muffin-tin XC on the angular quadrature: LDA directly; GGA via
    mt_xc_gga. Collinear magnetism via mag_lm (z-component in real
    harmonics)."""
    import jax.numpy as jnp

    if xc.is_gga:
        return mt_xc_gga(rho_lm, r, xc, sht, mag_lm)

    rho_pt = np.maximum(sht.to_grid(rho_lm), 1e-12)  # [np, nr]
    if mag_lm is None:
        res = xc.evaluate(jnp.asarray(rho_pt.ravel()))
        v = np.asarray(res["v"]).reshape(rho_pt.shape)
        e = np.asarray(res["e"]).reshape(rho_pt.shape)  # energy per volume
        return sht.to_lm(v), sht.to_lm(e), None
    m_pt = sht.to_grid(mag_lm)
    m_pt = np.clip(m_pt, -rho_pt + 1e-12, rho_pt - 1e-12)
    up = 0.5 * (rho_pt + m_pt).ravel()
    dn = 0.5 * (rho_pt - m_pt).ravel()
    res = xc.evaluate_polarized(jnp.asarray(up), jnp.asarray(dn))
    vu = np.asarray(res["v_up"]).reshape(rho_pt.shape)
    vd = np.asarray(res["v_dn"]).reshape(rho_pt.shape)
    e = np.asarray(res["e"]).reshape(rho_pt.shape)
    return (
        sht.to_lm(0.5 * (vu + vd)),
        sht.to_lm(e),
        sht.to_lm(0.5 * (vu - vd)),
    )


def gcart_box(dims, lattice) -> np.ndarray:
    """[3, n1, n2, n3] cartesian G of every FFT-box frequency (for full-box
    spectral gradients in the interstitial GGA)."""
    recip = 2.0 * np.pi * np.linalg.inv(np.asarray(lattice)).T  # rows b_i
    freqs = [np.fft.fftfreq(n, d=1.0 / n) for n in dims]
    m = np.stack(np.meshgrid(*freqs, indexing="ij"), axis=-1)  # [n1,n2,n3,3]
    return np.einsum("xyzi,ij->jxyz", m, recip)


def _box_grad(f_r, gbox):
    fg = np.fft.fftn(f_r)
    return np.stack(
        [np.real(np.fft.ifftn(1j * gbox[i] * fg)) for i in range(3)]
    )


def _box_div(vec_r, gbox):
    out = np.zeros(vec_r.shape[1:])
    for i in range(3):
        out += np.real(np.fft.ifftn(1j * gbox[i] * np.fft.fftn(vec_r[i])))
    return out


def interstitial_xc(rho_r, xc, mag_r=None, gbox=None):
    """(vxc_r, exc_density_r[, bxc_r]) pointwise on the FFT grid (full
    cell; the integrals later weight by the step function). Collinear
    magnetism via mag_r (z-component). GGA needs gbox (gcart_box) for the
    full-box spectral gradient and the -div(vsigma grad n) term — exactly
    the PP-PW smooth-grid scheme (reference xc.cpp GGA branch)."""
    import jax.numpy as jnp

    shape = rho_r.shape
    rho = np.maximum(rho_r, 1e-12)
    if xc.is_gga and gbox is None:
        raise ValueError("interstitial_xc: GGA functional requires gbox")
    if mag_r is None:
        if xc.is_gga:
            g = _box_grad(rho_r, gbox)
            sigma = np.sum(g * g, axis=0)
            res = xc.evaluate(jnp.asarray(rho.ravel()), jnp.asarray(sigma.ravel()))
            v = np.asarray(res["v"]).reshape(shape)
            vs = np.asarray(res["vsigma"]).reshape(shape)
            v = v - _box_div(2.0 * vs[None] * g, gbox)
        else:
            res = xc.evaluate(jnp.asarray(rho.ravel()))
            v = np.asarray(res["v"]).reshape(shape)
        e = np.asarray(res["e"]).reshape(shape)
        return v, e
    m = np.clip(mag_r, -rho + 1e-12, rho - 1e-12)
    up, dn = 0.5 * (rho + m), 0.5 * (rho - m)
    if xc.is_gga:
        gu = _box_grad(up, gbox)
        gd = _box_grad(dn, gbox)
        suu = np.sum(gu * gu, axis=0)
        sud = np.sum(gu * gd, axis=0)
        sdd = np.sum(gd * gd, axis=0)
        res = xc.evaluate_polarized(
            jnp.asarray(up.ravel()), jnp.asarray(dn.ravel()),
            jnp.asarray(suu.ravel()), jnp.asarray(sud.ravel()),
            jnp.asarray(sdd.ravel()),
        )
        vu = np.asarray(res["v_up"]).reshape(shape)
        vd = np.asarray(res["v_dn"]).reshape(shape)
        vsuu = np.asarray(res["vsigma_uu"]).reshape(shape)
        vsud = np.asarray(res["vsigma_ud"]).reshape(shape)
        vsdd = np.asarray(res["vsigma_dd"]).reshape(shape)
        vu = vu - _box_div(2.0 * vsuu[None] * gu + vsud[None] * gd, gbox)
        vd = vd - _box_div(2.0 * vsdd[None] * gd + vsud[None] * gu, gbox)
    else:
        res = xc.evaluate_polarized(jnp.asarray(up.ravel()), jnp.asarray(dn.ravel()))
        vu = np.asarray(res["v_up"]).reshape(shape)
        vd = np.asarray(res["v_dn"]).reshape(shape)
    e = np.asarray(res["e"]).reshape(shape)
    return 0.5 * (vu + vd), e, 0.5 * (vu - vd)
