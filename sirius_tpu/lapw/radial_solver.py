"""Radial Schrödinger / scalar-relativistic / Dirac solvers.

Reference: src/radial/radial_solver.hpp. The second-order radial problem
for p(r) = u(r) r decouples into

  p'(r) = 2 M q(r) + p(r)/r           (+ energy-derivative source terms)
  q'(r) = (V - E + l(l+1)/(2 M r^2)) p(r) - q(r)/r - chi(r)

with the relativistic mass M = 1 (none), 1 + a^2/2 (E - V) (Koelling-
Harmon), 1 - a^2/2 V (ZORA), M0/(1 - a^2 E / (2 M0)) (IORA). The first
energy derivative solves the same system with source terms (reference
radial_solver.hpp:136-200). The 4-component Dirac radial system for core
states is

  P' = -(kappa/r) P + a (E - V + 2/a^2) Q
  Q' =  (kappa/r) Q - a (E - V) P

Integration is RK4 on the species' own (nonuniform) grid with the
potential presampled at the nodes and interval midpoints (one spline pass
per grid, not per step); bound states use node-count bisection. All in
Hartree atomic units (c = 137.035999139).
"""

from __future__ import annotations

import numpy as np

from sirius_tpu.lapw.quad import rint

SPEED_OF_LIGHT = 137.035999139
ALPHA = 1.0 / SPEED_OF_LIGHT
SQ_ALPHA_HALF = 0.5 * ALPHA * ALPHA

RELATIVITIES = ("none", "koelling_harmon", "zora", "iora", "dirac")


def _with_midpoints(r, f):
    """[2n-1] array of f at nodes and interval midpoints (spline once)."""
    from sirius_tpu.core.radial import Spline

    s = Spline(r, f)
    mid = 0.5 * (r[:-1] + r[1:])
    out = np.empty(2 * len(r) - 1)
    out[0::2] = f
    out[1::2] = s(mid)
    return out


def _mass(rel: str, E: float, v):
    if rel == "none":
        return np.ones_like(v)
    if rel == "koelling_harmon":
        return 1.0 + SQ_ALPHA_HALF * (E - v)
    if rel == "zora":
        return 1.0 - SQ_ALPHA_HALF * v
    if rel == "iora":
        m0 = 1.0 - SQ_ALPHA_HALF * v
        return m0 / (1.0 - SQ_ALPHA_HALF * E / m0)
    raise ValueError(rel)



def _indicial_start(r, v2, l: int, rel: str):
    """Series start values (p0, q0) at r[0] — shared by the numpy and jax
    integrators (relativistic r^b for the scalar-relativistic cases at a
    nuclear-singular potential, r^{l+1} otherwise)."""
    zn_eff = max(-v2[0] * r[0], 0.0)
    if rel in ("koelling_harmon", "zora", "iora") and zn_eff > 1e-8:
        a0 = l * (l + 1) + 1.0 - (ALPHA * zn_eff) ** 2
        b0 = 0.5 * (1.0 + np.sqrt(1.0 + 4.0 * a0))
        p0 = r[0] ** b0
        q0 = p0 * (b0 - 1.0) / (zn_eff * ALPHA * ALPHA)
    else:
        p0 = r[0] ** (l + 1)
        q0 = 0.5 * l * r[0] ** l
    return float(p0), float(q0)


def _tri_samples(r, v2):
    """Per-interval (start, mid, end) sample index map and arrays."""
    n = len(r)
    r2 = np.empty(2 * n - 1)
    r2[0::2] = r
    r2[1::2] = 0.5 * (r[:-1] + r[1:])
    idx = np.arange(n - 1)
    tri = np.stack([2 * idx, 2 * idx + 1, 2 * idx + 2], axis=1)
    return r2, tri


def _jax_mass(rel: str):
    """jnp mass function of (E, v) for a relativity flavor (the jnp twin
    of _mass; kept in one place so the variants cannot desynchronize)."""
    import jax.numpy as jnp

    def mass(E, v):
        if rel == "none":
            return jnp.ones_like(v)
        if rel == "koelling_harmon":
            return 1.0 + SQ_ALPHA_HALF * (E - v)
        if rel == "zora":
            return 1.0 - SQ_ALPHA_HALF * v
        m0 = 1.0 - SQ_ALPHA_HALF * v
        return m0 / (1.0 - SQ_ALPHA_HALF * E / m0)

    return mass


_SCAN_CACHE: dict = {}


def _jax_rk4(n: int, rel: str, has_src: bool):
    """Jitted lax.scan RK4 outward integrator for an n-point grid.

    Same arithmetic as the numpy loop below (same coefficient samples at
    nodes and interval midpoints, same 1e60 renormalization, same
    node-count semantics), compiled once per (n, rel, has_src) — the
    radial solver is the LAPW hot spot (60 of 128 s/iteration in the
    test12 profile came from the python RK4 loop)."""
    key = (n, rel, has_src)
    fn = _SCAN_CACHE.get(key)
    if fn is not None:
        return fn
    import jax
    import jax.numpy as jnp

    mass = _jax_mass(rel)

    def run(E, hsteps, r3, v3, sp3, sq3, ll, p0, q0, ncut):
        """hsteps: [n-1]; r3/v3/(sp3/sq3): [n-1, 3] start/mid/end samples.
        ncut: steps beyond ncut are frozen (h=0 equivalent)."""
        m3 = mass(E, v3)
        a_pq = 2.0 * m3
        a_qp = v3 - E + ll / (m3 * r3 * r3)
        inv_r = 1.0 / r3

        def f(j, pp, qq, x):
            dp = x["a_pq"][j] * qq + pp * x["inv_r"][j]
            dq = x["a_qp"][j] * pp - qq * x["inv_r"][j]
            if has_src:
                dp = dp + x["sp"][j]
                dq = dq + x["sq"][j]
            return dp, dq

        def step(carry, x):
            yp, yq, nodes, ls = carry
            h = x["h"]
            k1p, k1q = f(0, yp, yq, x)
            k2p, k2q = f(1, yp + 0.5 * h * k1p, yq + 0.5 * h * k1q, x)
            k3p, k3q = f(1, yp + 0.5 * h * k2p, yq + 0.5 * h * k2q, x)
            k4p, k4q = f(2, yp + h * k3p, yq + h * k3q, x)
            ypn = yp + (h / 6.0) * (k1p + 2 * k2p + 2 * k3p + k4p)
            yqn = yq + (h / 6.0) * (k1q + 2 * k2q + 2 * k3q + k4q)
            live = x["live"]
            ypn = jnp.where(live, ypn, yp)
            yqn = jnp.where(live, yqn, yq)
            s = jnp.maximum(jnp.abs(ypn), jnp.abs(yqn))
            do_scale = live & (s > 1e60)
            scale = jnp.where(do_scale, s, 1.0)
            ypn = ypn / scale
            yqn = yqn / scale
            ls = ls + jnp.log(scale)
            nodes = nodes + jnp.where(live & (ypn * yp < 0), 1, 0)
            return (ypn, yqn, nodes, ls), (ypn, yqn, ls)

        # scan xs leaves carry leading axis n-1; the per-step slice of a
        # [n-1, 3] coefficient array is [3], indexed by j inside f
        live = jnp.arange(n - 1, dtype=jnp.int32) < ncut
        xs = {
            "h": hsteps, "a_pq": a_pq, "a_qp": a_qp, "inv_r": inv_r,
            "live": live,
        }
        if has_src:
            xs["sp"] = sp3
            xs["sq"] = sq3
        (ypf, yqf, nodes, lsf), (ps, qs, lss) = jax.lax.scan(
            step, (p0, q0, 0, 0.0), xs
        )
        return ps, qs, lss, nodes, lsf

    fn = jax.jit(run)
    _SCAN_CACHE[key] = fn
    return fn


def _use_jax_solver() -> bool:
    import os

    return os.environ.get("SIRIUS_TPU_NUMPY_RADIAL", "") != "1"


_BATCH_CACHE: dict = {}


def _jax_rk4_nodes(n: int, rel: str):
    """Carry-only vmapped variant of _jax_rk4: for an energy VECTOR,
    returns (nodes [m], p(R) [m], q(R) [m]) in the final renormalization
    frame — the primitive behind the K-section bound-state and Enu
    searches (no per-point storage, so the scan is light)."""
    key = (n, rel)
    fn = _BATCH_CACHE.get(key)
    if fn is not None:
        return fn
    import jax
    import jax.numpy as jnp

    mass = _jax_mass(rel)

    def run_one(E, hsteps, r3, v3, ll, p0, q0):
        m3 = mass(E, v3)
        a_pq = 2.0 * m3
        a_qp = v3 - E + ll / (m3 * r3 * r3)
        inv_r = 1.0 / r3

        def f(j, pp, qq, x):
            return (
                x["a_pq"][j] * qq + pp * x["inv_r"][j],
                x["a_qp"][j] * pp - qq * x["inv_r"][j],
            )

        def step(carry, x):
            yp, yq, nodes, ls = carry
            h = x["h"]
            k1p, k1q = f(0, yp, yq, x)
            k2p, k2q = f(1, yp + 0.5 * h * k1p, yq + 0.5 * h * k1q, x)
            k3p, k3q = f(1, yp + 0.5 * h * k2p, yq + 0.5 * h * k2q, x)
            k4p, k4q = f(2, yp + h * k3p, yq + h * k3q, x)
            ypn = yp + (h / 6.0) * (k1p + 2 * k2p + 2 * k3p + k4p)
            yqn = yq + (h / 6.0) * (k1q + 2 * k2q + 2 * k3q + k4q)
            s = jnp.maximum(jnp.abs(ypn), jnp.abs(yqn))
            scale = jnp.where(s > 1e60, s, 1.0)
            ypn = ypn / scale
            yqn = yqn / scale
            ls = ls + jnp.log(scale)
            nodes = nodes + jnp.where(ypn * yp < 0, 1, 0)
            return (ypn, yqn, nodes, ls), None

        xs = {"h": hsteps, "a_pq": a_pq, "a_qp": a_qp, "inv_r": inv_r}
        (ypf, yqf, nodes, lsf), _ = jax.lax.scan(step, (p0, q0, 0, 0.0), xs)
        return nodes, ypf, yqf, lsf

    fn = jax.jit(
        jax.vmap(run_one, in_axes=(0, None, None, None, None, None, None))
    )
    _BATCH_CACHE[key] = fn
    return fn


class _BatchEval:
    """Batched (vmapped-over-E) evaluator for one (grid, potential, l):
    nodes/boundary values for an energy vector in one compiled call."""

    def __init__(self, r, veff, l: int, rel: str, v2=None):
        import jax.numpy as jnp

        n = len(r)
        if v2 is None:
            v2 = _with_midpoints(r, veff)
        r2, tri = _tri_samples(r, v2)
        p0, q0 = _indicial_start(r, v2, l, rel)
        self._fn = _jax_rk4_nodes(n, rel)
        self._args = (
            jnp.asarray(np.diff(r)), jnp.asarray(r2[tri]),
            jnp.asarray(v2[tri]), float(0.5 * l * (l + 1)),
            float(p0), float(q0),
        )
        self._rel = rel
        self._vR = float(veff[-1])
        self._R = float(r[-1])

    def __call__(self, evec):
        import jax.numpy as jnp

        nodes, pR, qR, lsf = self._fn(jnp.asarray(np.atleast_1d(evec)), *self._args)
        return (
            np.asarray(nodes), np.asarray(pR), np.asarray(qR),
            np.asarray(lsf),
        )

    def pderiv(self, evec):
        """p'(R) = 2 M(R) q(R) + p(R)/R per energy, in the final
        renormalization frame — identical to the numpy path's use of the
        stored (renormalized) p, q arrays in find_enu_band."""
        nodes, pR, qR, lsf = self(evec)
        m = np.array([
            float(_mass(self._rel, float(e), np.asarray([self._vR]))[0])
            for e in np.atleast_1d(evec)
        ])
        return 2.0 * m * qR + pR / self._R, nodes


def integrate_outward(r, veff, l: int, E: float, rel: str = "none",
                      p_prev=None, q_prev=None, mderiv: int = 0,
                      v2=None, ncut: int | None = None):
    """RK4 outward integration. Returns (p, q, num_nodes).

    p_prev/q_prev: (2n-1)-sampled previous-order arrays for mderiv=1 (use
    _with_midpoints); v2: optional presampled potential (2n-1) to amortize
    the spline across bisection iterations."""
    if rel == "dirac":
        raise ValueError("use find_bound_state_dirac for Dirac")
    n = len(r)
    if v2 is None:
        v2 = _with_midpoints(r, veff)
    if _use_jax_solver():
        import jax.numpy as jnp

        has_src = mderiv >= 1
        kh = rel in ("koelling_harmon", "iora")
        ll2 = 0.5 * l * (l + 1)
        r2, tri = _tri_samples(r, v2)
        sp3 = sq3 = np.zeros((n - 1, 3))
        if has_src:
            m2 = _mass(rel, E, v2)
            if kh:
                srcp = mderiv * ALPHA * ALPHA * q_prev
                srcq = -mderiv * (
                    1.0 + ll2 * ALPHA * ALPHA / (2.0 * m2 * m2 * r2 * r2)
                ) * p_prev
            else:
                srcp = np.zeros_like(v2)
                srcq = -mderiv * p_prev
            sp3 = srcp[tri]
            sq3 = srcq[tri]
        p0, q0 = _indicial_start(r, v2, l, rel)
        fn = _jax_rk4(n, rel, has_src)
        ps, qs, lss, nodes, lsf = fn(
            float(E), jnp.asarray(np.diff(r)), jnp.asarray(r2[tri]),
            jnp.asarray(v2[tri]), jnp.asarray(sp3), jnp.asarray(sq3),
            float(ll2), float(p0), float(q0),
            int(n - 1 if ncut is None else min(ncut, n) - 1),
        )
        p = np.empty(n)
        q = np.empty(n)
        p[0], q[0] = p0, q0
        ls = np.asarray(lss)
        lsf = float(lsf)
        # reconstruct the final renormalization frame: stored values carry
        # the cumulative scale at their own step; bring the prefix into the
        # final frame (exp of a NEGATIVE number — never overflows)
        fac = np.exp(ls - lsf)
        p[1:] = np.asarray(ps) * fac
        q[1:] = np.asarray(qs) * fac
        p[0] *= np.exp(-lsf)
        q[0] *= np.exp(-lsf)
        return p, q, int(nodes)
    r2 = np.empty(2 * n - 1)
    r2[0::2] = r
    r2[1::2] = 0.5 * (r[:-1] + r[1:])
    m2 = _mass(rel, E, v2)
    ll2 = 0.5 * l * (l + 1)
    # coefficient arrays at the 2n-1 sample points
    a_pq = 2.0 * m2                      # p' = a_pq q + p/r
    a_qp = v2 - E + ll2 / (m2 * r2 * r2)  # q' = a_qp p - q/r (- sources)
    inv_r = 1.0 / r2
    kh = rel in ("koelling_harmon", "iora")
    if mderiv >= 1:
        # (h - E) u_m = m u_{m-1}: the m-th energy derivative solves the
        # same system with the (m-1)-th solution as source, scaled by m
        # (reference radial_solver.hpp solve() m=1,2 branches)
        src_p = mderiv * ALPHA * ALPHA * q_prev if kh else np.zeros_like(v2)
        src_q = -mderiv * (1.0 + ll2 * ALPHA * ALPHA / (2.0 * m2 * m2 * r2 * r2)) * p_prev if kh \
            else -mderiv * p_prev
    p = np.empty(n)
    q = np.empty(n)
    # starting values at r0: relativistic indicial behavior r^b near the
    # nuclear singularity for the scalar-relativistic cases (reference
    # radial_solver.hpp:535-543), non-relativistic r^{l+1} otherwise
    zn_eff = max(-v2[0] * r[0], 0.0)
    if rel in ("koelling_harmon", "zora", "iora") and zn_eff > 1e-8:
        a0 = l * (l + 1) + 1.0 - (ALPHA * zn_eff) ** 2
        b0 = 0.5 * (1.0 + np.sqrt(1.0 + 4.0 * a0))
        p[0] = r[0] ** b0
        q[0] = p[0] * (b0 - 1.0) / (zn_eff * ALPHA * ALPHA)
    else:
        p[0] = r[0] ** (l + 1)
        q[0] = 0.5 * l * r[0] ** l
    yp, yq = p[0], q[0]
    nodes = 0

    def f(i2, pp, qq):
        dp = a_pq[i2] * qq + pp * inv_r[i2]
        dq = a_qp[i2] * pp - qq * inv_r[i2]
        if mderiv >= 1:
            dp += src_p[i2]
            dq += src_q[i2]
        return dp, dq

    for i in range(n - 1):
        h = r[i + 1] - r[i]
        i0, im, i1 = 2 * i, 2 * i + 1, 2 * i + 2
        k1p, k1q = f(i0, yp, yq)
        k2p, k2q = f(im, yp + 0.5 * h * k1p, yq + 0.5 * h * k1q)
        k3p, k3q = f(im, yp + 0.5 * h * k2p, yq + 0.5 * h * k2q)
        k4p, k4q = f(i1, yp + h * k3p, yq + h * k3q)
        yp_new = yp + (h / 6.0) * (k1p + 2 * k2p + 2 * k3p + k4p)
        yq = yq + (h / 6.0) * (k1q + 2 * k2q + 2 * k3q + k4q)
        if abs(yp_new) > 1e60 or abs(yq) > 1e60:
            s = max(abs(yp_new), abs(yq))
            yp_new /= s
            yq /= s
            p[: i + 1] /= s
            q[: i + 1] /= s
        if yp_new * yp < 0:
            nodes += 1
        yp = yp_new
        p[i + 1] = yp
        q[i + 1] = yq
    return p, q, nodes


def surface_derivatives(r, veff, l: int, E: float, rel: str = "none"):
    """(u(R), u'(R), p, q): boundary values for APW matching.

    u = p/r; u' = (p' - u)/r = 2 M q / r (from the p' equation)."""
    p, q, _ = integrate_outward(r, veff, l, E, rel)
    R = r[-1]
    m = float(_mass(rel, E, np.asarray([veff[-1]]))[0])
    return p[-1] / R, 2.0 * m * q[-1] / R, p, q


def _refine_grid(r, veff, rounds: int):
    """Insert interval midpoints `rounds` times (spline-resampled V): RK4's
    O(h^4) truncation error drops ~8-16x per round. The reference reaches
    the same accuracy class with GSL adaptive rkf45
    (radial_solver.hpp:344 integrate_forward_gsl); deep core s-states need
    it — at the species grids shipped with the FP decks the unrefined
    shooting carries ~1e-6 Ha per s-state (Z~28), which sums to the
    1e-5-class total-energy gap seen on heavy-atom LAPW decks."""
    for _ in range(rounds):
        vf = _with_midpoints(r, veff)
        rf = np.empty(2 * len(r) - 1)
        rf[0::2] = r
        rf[1::2] = 0.5 * (r[:-1] + r[1:])
        r, veff = rf, vf
    return r, veff


def find_bound_state(r, veff, l: int, n: int, rel: str = "none",
                     e_lo: float = -200.0, e_hi: float = 10.0,
                     tol: float = 1e-10, max_iter: int = 200,
                     refine: int = 1):
    """Bound state with principal quantum number n (n - l - 1 nodes) by
    node-count bisection. Returns (E, u(r) normalized to int u^2 r^2 = 1).
    `refine` midpoint-insertion rounds sharpen the RK4 shooting (core
    states on species grids; see _refine_grid)."""
    if refine:
        r_nodes = r
        stride = 2 ** refine
        r, veff = _refine_grid(np.asarray(r, float), np.asarray(veff, float), refine)
        E, u = find_bound_state(r, veff, l, n, rel, e_lo, e_hi, tol,
                                max_iter, refine=0)
        u = u[::stride]
        nrm = np.sqrt(rint(r_nodes * r_nodes * u * u, r_nodes))
        return E, u / nrm
    target_nodes = n - l - 1
    assert target_nodes >= 0
    v2 = _with_midpoints(r, veff)
    lo, hi = e_lo, e_hi
    if _use_jax_solver():
        # K-section search: one vmapped call shrinks the bracket K-1 fold
        # (the node count is monotonic in E)
        be = _BatchEval(r, veff, l, rel, v2=v2)
        K = 17
        for _ in range(max_iter):
            es = np.linspace(lo, hi, K)
            nd = be(es)[0]
            above = np.nonzero(nd > target_nodes)[0]
            j = int(above[0]) if len(above) else K - 1
            lo, hi = es[max(j - 1, 0)], es[j]
            if hi - lo < tol * max(1.0, abs(lo)):
                break
    else:
        for _ in range(max_iter):
            mid = 0.5 * (lo + hi)
            _, _, nd = integrate_outward(r, veff, l, mid, rel, v2=v2)
            if nd > target_nodes:
                hi = mid
            else:
                lo = mid
            if hi - lo < tol * max(1.0, abs(lo)):
                break
    E = 0.5 * (lo + hi)
    ncut = _decay_cutoff_index(r, veff, l, E)
    if _use_jax_solver():
        # fixed-shape solve with frozen tail (one compilation per grid
        # length instead of one per truncation point)
        p, _, _ = integrate_outward(r, veff, l, E, rel, v2=v2, ncut=ncut)
        p[ncut:] = 0.0
    else:
        p_c, _, _ = integrate_outward(r[:ncut], veff[:ncut], l, E, rel)
        p = np.zeros(len(r))
        p[:ncut] = p_c
    p = _cut_forbidden_tail(p, r, veff, l, E)
    u = p / r
    nrm = np.sqrt(rint(p * p, r))
    return E, u / nrm


def _decay_cutoff_index(r, veff, l: int, E: float) -> int:
    """Index bounding the solve domain for a bound state: past the
    classical turning point the physical solution decays like
    e^{-kappa (r - r_t)}; integrating much beyond underflows it to zero
    while the junk solution overflows. Keep ~30 decay lengths."""
    vl = veff + 0.5 * l * (l + 1) / np.maximum(r, 1e-30) ** 2
    inside = np.nonzero(E > vl)[0]
    if not len(inside):
        return len(r)
    rt = r[inside[-1]]
    kappa = np.sqrt(max(2.0 * abs(E), 1e-3))
    rmax = rt + 30.0 / kappa
    ncut = int(np.searchsorted(r, rmax)) + 1
    return max(8, min(ncut, len(r)))


def _cut_forbidden_tail(p, r, veff, l: int, E: float, q=None):
    """Zero the outward solution beyond its |p| minimum past the classical
    turning point: outward integration amplifies the e^{+kappa r} junk
    solution there (for deep states the overflow rescaling even makes the
    junk the global maximum), so the tail carries no physics."""
    vl = veff + 0.5 * l * (l + 1) / np.maximum(r, 1e-30) ** 2
    inside = np.nonzero(E > vl)[0]
    it0 = int(inside[-1]) if len(inside) else 0
    if it0 >= len(p) - 2 or it0 < 3:
        return p if q is None else (p, q)
    # exact zeros are padding from a truncated solve, not the physical
    # minimum — exclude them from the decay/junk crossover search
    tail = np.abs(p[it0:]).astype(float)
    tail[tail == 0.0] = np.inf
    if not np.isfinite(tail).any():
        return p if q is None else (p, q)
    icut = it0 + int(np.argmin(tail))
    if 3 <= icut < len(p) - 1 and np.abs(p[:icut]).max() > 0:
        p = p.copy()
        p[icut:] = 0.0
        if q is not None:
            q = q.copy()
            q[icut:] = 0.0
    return p if q is None else (p, q)


def find_enu_band(r, veff, l: int, n: int, rel: str = "none"):
    """Linearization energy as the CENTER of the (n, l) band:
    (ebot + etop)/2 with etop the energy where u(R) = 0 at node count
    n - l - 1 and ebot where p'(R) = 0 (reference Enu_finder::find_enu,
    radial_solver.hpp:1172-1276, auto_enu = 1)."""
    etop, _ = find_bound_state(r, veff, l, n, rel)
    v2 = _with_midpoints(r, veff)
    R = r[-1]

    if _use_jax_solver():
        be = _BatchEval(r, veff, l, rel, v2=v2)

        def pderiv(E):
            return float(be.pderiv([E])[0][0])

        def pderiv_batch(es):
            return be.pderiv(es)[0]
    else:
        def pderiv(E):
            p, q, _ = integrate_outward(r, veff, l, E, rel, v2=v2)
            m = float(_mass(rel, E, np.asarray([veff[-1]]))[0])
            return 2.0 * m * q[-1] + p[-1] / R

        def pderiv_batch(es):
            return np.array([pderiv(float(e)) for e in es])

    sd = pderiv(etop)
    # expansion: the same doubling ladder as the scalar path, but evaluated
    # as one batch (e0_k = etop - (2^{k+1} - 2) * 1e-8)
    denus = 1e-8 * 2.0 ** np.arange(1, 62)
    denus = denus[denus <= 20 * 2]
    offsets = np.concatenate([[0.0], np.cumsum(denus)])
    ladder = etop - offsets
    dv = pderiv_batch(ladder)
    cross = np.nonzero(dv * sd <= 0)[0]
    if not len(cross):
        # no p'(R) sign change within ~40 Ha below the band top: the band
        # has no well-defined bottom here — fall back to the top
        return etop, etop, etop
    j = int(cross[0])
    e1, e2 = ladder[j], ladder[max(j - 1, 0)]
    for _ in range(14):
        es = np.linspace(e1, e2, 9)
        dvs = pderiv_batch(es)
        # first index (from the top, e2 side) still on sd's side
        same = dvs * sd > 0
        # es ascending: e1..e2; the crossing lies between the last
        # non-same and the first same index going up
        idx_same = np.nonzero(same)[0]
        if len(idx_same):
            j2 = int(idx_same[0])
            e1, e2 = es[max(j2 - 1, 0)], es[j2]
        else:
            e1, e2 = es[-2], es[-1]
        if np.abs(dvs).min() < 1e-8 or (e2 - e1) < 1e-12:
            break
    ebot = 0.5 * (e1 + e2)
    return 0.5 * (ebot + etop), ebot, etop


_DIRAC_CACHE: dict = {}


def _jax_dirac(n: int, store: bool):
    """Jitted (vmapped over E when store=False) Dirac RK4 integrator —
    same arithmetic as the numpy loop in find_bound_state_dirac."""
    key = (n, store)
    fn = _DIRAC_CACHE.get(key)
    if fn is not None:
        return fn
    import jax
    import jax.numpy as jnp

    two_c2 = 2.0 / (ALPHA * ALPHA)

    def run_one(E, kappa, hsteps, r3, v3, p0, q0, ncut):
        aPQ = ALPHA * (E - v3 + two_c2)
        aQP = -ALPHA * (E - v3)
        inv_r = 1.0 / r3

        def f(j, pp, qq, x):
            return (
                -kappa * x["inv_r"][j] * pp + x["aPQ"][j] * qq,
                kappa * x["inv_r"][j] * qq + x["aQP"][j] * pp,
            )

        def step(carry, x):
            yp, yq, nodes, ls = carry
            h = x["h"]
            k1p, k1q = f(0, yp, yq, x)
            k2p, k2q = f(1, yp + 0.5 * h * k1p, yq + 0.5 * h * k1q, x)
            k3p, k3q = f(1, yp + 0.5 * h * k2p, yq + 0.5 * h * k2q, x)
            k4p, k4q = f(2, yp + h * k3p, yq + h * k3q, x)
            ypn = yp + (h / 6.0) * (k1p + 2 * k2p + 2 * k3p + k4p)
            yqn = yq + (h / 6.0) * (k1q + 2 * k2q + 2 * k3q + k4q)
            live = x["live"]
            ypn = jnp.where(live, ypn, yp)
            yqn = jnp.where(live, yqn, yq)
            s = jnp.maximum(jnp.abs(ypn), jnp.abs(yqn))
            do_scale = live & (s > 1e60)
            scale = jnp.where(do_scale, s, 1.0)
            ypn = ypn / scale
            yqn = yqn / scale
            ls = ls + jnp.log(scale)
            nodes = nodes + jnp.where(live & (ypn * yp < 0), 1, 0)
            return (ypn, yqn, nodes, ls), (
                (ypn, yqn, ls) if store else None
            )

        live = jnp.arange(n - 1, dtype=jnp.int32) < ncut
        xs = {"h": hsteps, "aPQ": aPQ, "aQP": aQP, "inv_r": inv_r,
              "live": live}
        carry, ys = jax.lax.scan(step, (p0, q0, 0, 0.0), xs)
        if store:
            return ys[0], ys[1], ys[2], carry[2], carry[3]
        return carry[2]

    if store:
        fn = jax.jit(run_one)
    else:
        fn = jax.jit(
            jax.vmap(run_one, in_axes=(0,) + (None,) * 7)
        )
    _DIRAC_CACHE[key] = fn
    return fn


def find_bound_state_dirac(r, veff, n: int, kappa: int,
                           e_lo: float = -5000.0, e_hi: float = 10.0,
                           tol: float = 1e-10, max_iter: int = 250,
                           refine: int = 1):
    """Dirac bound state (deep core levels). kappa = -(l+1) for
    j = l + 1/2, kappa = l for j = l - 1/2; energies exclude the rest
    mass. Returns (E, g(r), f(r)) with int (g^2 + f^2) r^2 = 1.
    `refine` rounds of midpoint insertion tighten the shooting accuracy
    (see _refine_grid)."""
    if refine:
        r_nodes = np.asarray(r, float)
        stride = 2 ** refine
        rf, vf = _refine_grid(r_nodes, np.asarray(veff, float), refine)
        E, g, f = find_bound_state_dirac(rf, vf, n, kappa, e_lo, e_hi, tol,
                                         max_iter, refine=0)
        g, f = g[::stride], f[::stride]
        nrm = np.sqrt(rint(r_nodes * r_nodes * (g * g + f * f), r_nodes))
        return E, g / nrm, f / nrm
    l = kappa if kappa > 0 else -kappa - 1
    target_nodes = n - l - 1
    v2 = _with_midpoints(r, veff)
    nmax = len(r)
    r2 = np.empty(2 * nmax - 1)
    r2[0::2] = r
    r2[1::2] = 0.5 * (r[:-1] + r[1:])
    inv_r = 1.0 / r2
    two_c2 = 2.0 / (ALPHA * ALPHA)

    # relativistic indicial series: P ~ r^gamma, Q0/P0 = (gamma+kappa)/(z a)
    # with gamma = sqrt(kappa^2 - (z a)^2) (point-nucleus behavior; FP
    # muffin-tin potentials are always nuclear-singular at the origin)
    zeff = max(-veff[0] * r[0], 1e-8)
    gamma = np.sqrt(max(kappa * kappa - (zeff * ALPHA) ** 2, 1e-12))

    def integrate(E, nstop=None):
        nn = nmax if nstop is None else nstop
        aPQ = ALPHA * (E - v2 + two_c2)
        aQP = -ALPHA * (E - v2)
        P = np.zeros(nmax)
        Q = np.zeros(nmax)
        P[0] = r[0] ** gamma
        Q[0] = P[0] * (gamma + kappa) / (zeff * ALPHA)
        yp, yq = P[0], Q[0]
        nodes = 0

        def f(i2, pp, qq):
            return (
                -kappa * inv_r[i2] * pp + aPQ[i2] * qq,
                kappa * inv_r[i2] * qq + aQP[i2] * pp,
            )

        for i in range(nn - 1):
            h = r[i + 1] - r[i]
            i0, im, i1 = 2 * i, 2 * i + 1, 2 * i + 2
            k1p, k1q = f(i0, yp, yq)
            k2p, k2q = f(im, yp + 0.5 * h * k1p, yq + 0.5 * h * k1q)
            k3p, k3q = f(im, yp + 0.5 * h * k2p, yq + 0.5 * h * k2q)
            k4p, k4q = f(i1, yp + h * k3p, yq + h * k3q)
            yp_new = yp + (h / 6.0) * (k1p + 2 * k2p + 2 * k3p + k4p)
            yq = yq + (h / 6.0) * (k1q + 2 * k2q + 2 * k3q + k4q)
            if abs(yp_new) > 1e60 or abs(yq) > 1e60:
                s = max(abs(yp_new), abs(yq))
                yp_new /= s
                yq /= s
                P[: i + 1] /= s
                Q[: i + 1] /= s
            if yp_new * yp < 0:
                nodes += 1
            yp = yp_new
            P[i + 1] = yp
            Q[i + 1] = yq
        return P, Q, nodes

    lo, hi = e_lo, e_hi
    if _use_jax_solver():
        import jax.numpy as jnp

        _r2d, tri = _tri_samples(r, v2)
        hsteps = jnp.asarray(np.diff(r))
        r3 = jnp.asarray(r2[tri])
        v3 = jnp.asarray(v2[tri])
        P0 = float(r[0] ** gamma)
        Q0 = float(P0 * (gamma + kappa) / (zeff * ALPHA))
        nodes_fn = _jax_dirac(nmax, store=False)
        K = 17
        for _ in range(max_iter):
            es = np.linspace(lo, hi, K)
            nd = np.asarray(nodes_fn(
                jnp.asarray(es), float(kappa), hsteps, r3, v3, P0, Q0,
                nmax - 1,
            ))
            above = np.nonzero(nd > target_nodes)[0]
            j = int(above[0]) if len(above) else K - 1
            lo, hi = es[max(j - 1, 0)], es[j]
            if hi - lo < tol * max(1.0, abs(lo)):
                break
        E = 0.5 * (lo + hi)
        ncut = _decay_cutoff_index(r, veff, l, E)
        ps, qs, lss, _, lsf = _jax_dirac(nmax, store=True)(
            float(E), float(kappa), hsteps, r3, v3, P0, Q0, ncut - 1
        )
        P = np.empty(nmax)
        Q = np.empty(nmax)
        fac = np.exp(np.asarray(lss) - float(lsf))
        P[0] = P0 * np.exp(-float(lsf))
        Q[0] = Q0 * np.exp(-float(lsf))
        P[1:] = np.asarray(ps) * fac
        Q[1:] = np.asarray(qs) * fac
        P[ncut:] = 0.0
        Q[ncut:] = 0.0
    else:
        for _ in range(max_iter):
            mid = 0.5 * (lo + hi)
            if integrate(mid)[2] > target_nodes:
                hi = mid
            else:
                lo = mid
            if hi - lo < tol * max(1.0, abs(lo)):
                break
        E = 0.5 * (lo + hi)
        P, Q, _ = integrate(E, nstop=_decay_cutoff_index(r, veff, l, E))
    P, Q = _cut_forbidden_tail(P, r, veff, l, E, q=Q)
    nrm = np.sqrt(rint(P * P + Q * Q, r))
    return E, (P / nrm) / r, (Q / nrm) / r


def radial_dme_chain(r, veff, l: int, E: float, rel: str = "none",
                     max_m: int = 1):
    """Energy-derivative chain u^(0..max_m) at E with spherical-Hamiltonian
    images: h u_m = E u_m + m u_{m-1}. u_0 normalized; u_1 orthogonalized
    to u_0 (the images stay consistent: (h-E)(u_1 - c u_0) = u_0). Returns
    list of (u, hu, uR, upR)."""
    v2 = _with_midpoints(r, veff)
    R = r[-1]

    def boundary(p, q, Ecur):
        m = float(_mass(rel, Ecur, np.asarray([veff[-1]]))[0])
        kh_extra = ALPHA * ALPHA * q[-1] if rel in ("koelling_harmon", "iora") else 0.0
        return p[-1] / R, (2.0 * m * q[-1] + kh_extra) / R

    p0, q0, _ = integrate_outward(r, veff, l, E, rel, v2=v2)
    nrm = np.sqrt(rint(p0 * p0, r))
    p0, q0 = p0 / nrm, q0 / nrm
    u0R, u0pR = boundary(p0, q0, E)
    chain = [[p0, q0]]
    out = [(p0 / r, E * (p0 / r), u0R, u0pR)]
    for m in range(1, max_m + 1):
        pp, qp = chain[m - 1]
        pm, qm, _ = integrate_outward(
            r, veff, l, E, rel,
            p_prev=_with_midpoints(r, pp), q_prev=_with_midpoints(r, qp),
            mderiv=m, v2=v2,
        )
        if m == 1:
            ov = rint(p0 * pm, r)
            pm = pm - ov * p0
            qm = qm - ov * q0
        umR, umpR = boundary(pm, qm, E)
        chain.append([pm, qm])
        um = pm / r
        hum = E * um + m * (chain[m - 1][0] / r)
        out.append((um, hum, umR, umpR))
    return out


def radial_solution_with_edot(r, veff, l: int, E: float, rel: str = "none"):
    """(u, udot, u(R), u'(R), udot(R), udot'(R)): the LAPW linearization
    pair. udot solves the inhomogeneous system with the m=1 source and is
    orthogonalized against u (reference Radial_solver::solve m=1 +
    Atom_symmetry_class orthogonalization)."""
    p, q, _ = integrate_outward(r, veff, l, E, rel)
    nrm = np.sqrt(rint(p * p, r))
    p, q = p / nrm, q / nrm
    pd, qd, _ = integrate_outward(
        r, veff, l, E, rel,
        p_prev=_with_midpoints(r, p), q_prev=_with_midpoints(r, q), mderiv=1,
    )
    ov = rint(p * pd, r)
    pd = pd - ov * p
    qd = qd - ov * q
    R = r[-1]
    m = float(_mass(rel, E, np.asarray([veff[-1]]))[0])
    kh_extra = ALPHA * ALPHA * q[-1] if rel in ("koelling_harmon", "iora") else 0.0
    u, up = p[-1] / R, 2.0 * m * q[-1] / R
    ud, udp = pd[-1] / R, (2.0 * m * qd[-1] + kh_extra) / R
    return p / r, pd / r, u, up, ud, udp
