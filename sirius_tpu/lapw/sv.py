"""Second-variational Hamiltonian with spin-orbit coupling and full
non-collinear B fields (FP-LAPW).

Re-design of the reference's apply_so_correction (hamiltonian.cpp:209),
Atom_symmetry_class::generate_so_radial_integrals
(atom_symmetry_class.cpp:697-735) and the non-collinear second-variational
branch of diagonalize_fp.hpp:343-507. The first-variational states span a
spin-degenerate basis; the second variation solves the 2 nev x 2 nev
problem

  H_sv = diag(e_fv) (x) 1 + [[ B_z + xi Lz , B_- + xi L_- ],
                             [ B_+ + xi L_+, -B_z - xi Lz ]]

with B_+- = B_x +- i B_y matrix elements over fv states and the SO
coupling xi projected through the MT expansion coefficients. The 1/2 of
the physical xi_phys L.S sits INSIDE xi (the radial integral carries
alpha^2/4 instead of alpha^2/2) — the reference's convention. Angular
matrices live in THIS package's real-harmonic convention
(ops/so._l_matrices_real), so phase conventions match the rest of the MT
machinery by construction.

The collinear path in lapw/scf_fp.py keeps its cheaper sigma_z solve; the
full non-collinear FP SCF (vector MT magnetization) is the remaining gap
and is documented as such in COVERAGE.md.
"""

from __future__ import annotations

import numpy as np

from sirius_tpu.lapw.quad import rint

ALPHA2_4 = 0.25 / 137.035999084**2  # (alpha/2)^2 = 1/(2c)^2


def so_weight(r: np.ndarray, v_sph: np.ndarray, zn: float) -> np.ndarray:
    """Radial SO weight w(r) = (alpha^2/4) [ dVe/dr * r + Z/r ] / M^2 with
    Ve the ELECTRONIC spherical potential (nucleus removed) and
    M = 1 - (alpha^2/2) V_sph (reference atom_symmetry_class.cpp:697-731);
    pair-independent, so hoisted out of the (u1, u2) double loop."""
    from sirius_tpu.core.radial import Spline

    ve = v_sph + zn / r  # electronic part
    dve = np.asarray(Spline(r, ve).derivative(r))
    m = 1.0 - 2.0 * ALPHA2_4 * v_sph
    return ALPHA2_4 * (dve * r + zn / r) / m**2


def so_radial_integral(r: np.ndarray, v_sph: np.ndarray, zn: float,
                       u1: np.ndarray, u2: np.ndarray) -> float:
    """xi(o1, o2) = int u1 u2 w(r) dr. Against the physical
    xi(r) = (alpha^2/2) (1/M^2) (1/r) dV/dr this carries a factor 1/2,
    absorbed by using L.S WITHOUT the 1/2 in the Hamiltonian blocks —
    mirrored from the reference convention."""
    return float(rint(u1 * u2 * so_weight(r, v_sph, zn), r))


def so_blocks_for_atom(basis, v_sph: np.ndarray, zn: float):
    """Per-atom SO coupling in the flat MT expansion index of
    density_fp.mt_index: four [nidx, nidx] complex blocks (uu, dd, ud, du)
    of xi * (Lz, -Lz, L-, L+) — reference apply_so_correction uses exactly
    these weights (m*xi on the diagonal spin blocks, the full ladder
    coefficient off-diagonal)."""
    from sirius_tpu.lapw.density_fp import mt_index
    from sirius_tpu.ops.so import _l_matrices_real

    r = basis.r
    rf, lm_of, rf_of = mt_index(basis, basis.lmax_apw)
    # l of each radial function, in the SAME aw-then-lo order mt_index
    # builds (its MtRadial entries carry their l)
    rf_l = [f.l for l in range(basis.lmax_apw + 1) for f in basis.aw[l]]
    rf_l += [f.l for f in basis.lo]
    nrf = len(rf)
    # xi over radial-function pairs of equal l; the pair-independent
    # weight is computed once
    w = so_weight(r, v_sph, zn)
    xi = np.zeros((nrf, nrf))
    for i in range(nrf):
        for j in range(nrf):
            if rf_l[i] == rf_l[j] and rf_l[i] > 0:
                xi[i, j] = float(rint(rf[i] * rf[j] * w, r))
    # angular matrices per l in the real-harmonic basis
    lmats = {}
    for l in range(max(rf_l) + 1):
        if l == 0:
            continue
        L, _C = _l_matrices_real(l)
        lmats[l] = tuple(L)
    nidx = len(lm_of)
    uu = np.zeros((nidx, nidx), dtype=np.complex128)
    dd = np.zeros_like(uu)
    ud = np.zeros_like(uu)
    du = np.zeros_like(uu)
    # lm -> (l, m-index) decode
    l_of_lm = []
    for l in range(64):
        l_of_lm += [l] * (2 * l + 1)
        if len(l_of_lm) > max(lm_of, default=0):
            break
    l_of_lm = np.asarray(l_of_lm)
    for p in range(nidx):
        lp = int(l_of_lm[lm_of[p]])
        if lp == 0:
            continue
        mp = lm_of[p] - lp * lp  # 0 .. 2l
        for q in range(nidx):
            lq = int(l_of_lm[lm_of[q]])
            if lq != lp:
                continue
            x = xi[rf_of[p], rf_of[q]]
            if x == 0.0:
                continue
            mq = lm_of[q] - lq * lq
            lx, ly, lz = lmats[lp]
            lm_ = lx[mp, mq] - 1j * ly[mp, mq]
            lp_ = lx[mp, mq] + 1j * ly[mp, mq]
            uu[p, q] += x * lz[mp, mq]
            dd[p, q] -= x * lz[mp, mq]
            ud[p, q] += x * lm_
            du[p, q] += x * lp_
    return uu, dd, ud, du


def sv_hamiltonian(e_fv: np.ndarray, bz_ij=None, bx_ij=None, by_ij=None,
                   so_proj=None) -> np.ndarray:
    """Assemble the 2 nev x 2 nev second-variational Hamiltonian.

    e_fv [nev]: first-variational energies; b*_ij [nev, nev]: B-field
    matrix elements over fv states (None = zero); so_proj: (uu, dd, ud,
    du) [nev, nev] blocks of the projected SO operator (None = no SO)."""
    nev = len(e_fv)
    z = np.zeros((nev, nev), dtype=np.complex128)
    bz = z if bz_ij is None else np.asarray(bz_ij, dtype=np.complex128)
    bx = z if bx_ij is None else np.asarray(bx_ij, dtype=np.complex128)
    by = z if by_ij is None else np.asarray(by_ij, dtype=np.complex128)
    so_uu = so_dd = so_ud = so_du = z
    if so_proj is not None:
        so_uu, so_dd, so_ud, so_du = (
            np.asarray(t, dtype=np.complex128) for t in so_proj
        )
    e = np.diag(np.asarray(e_fv, float))
    h = np.zeros((2 * nev, 2 * nev), dtype=np.complex128)
    h[:nev, :nev] = e + bz + so_uu
    h[nev:, nev:] = e - bz + so_dd
    h[:nev, nev:] = (bx - 1j * by) + so_ud
    h[nev:, :nev] = (bx + 1j * by) + so_du
    return 0.5 * (h + h.conj().T)


def project_so(so_blocks, W: np.ndarray):
    """Project per-atom MT SO blocks through the MT expansion matrix
    W [nidx, nev] -> four [nev, nev] fv-basis blocks."""
    return tuple(W.conj().T @ b @ W for b in so_blocks)
