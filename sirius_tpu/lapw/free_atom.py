"""Free-atom Kohn-Sham solver and FP species generator.

Re-design of the reference's `apps/atoms/atom.cpp` + `src/core/atomic_data.hpp`:
solve the isolated spherical atom self-consistently on a log grid with the
package's own radial bound-state solvers (Schroedinger / ZORA / Dirac) and
analytic XC, then emit the species JSON the FP-LAPW path consumes
(core/valence partition by a core-energy cutoff, APW/LAPW descriptors,
semicore local orbitals, and the free-atom density used for the initial
superposition). Unlike the reference there is no vendored NIST table dump:
ground-state configurations are generated from the aufbau filling plus the
standard exception list.

Validated against the NIST LSD reference energies (spin-restricted LDA-VWN)
in tests/test_free_atom.py.
"""

from __future__ import annotations

import json

import numpy as np

SYMBOLS = (
    "H He Li Be B C N O F Ne Na Mg Al Si P S Cl Ar "
    "K Ca Sc Ti V Cr Mn Fe Co Ni Cu Zn Ga Ge As Se Br Kr "
    "Rb Sr Y Zr Nb Mo Tc Ru Rh Pd Ag Cd In Sn Sb Te I Xe "
    "Cs Ba La Ce Pr Nd Pm Sm Eu Gd Tb Dy Ho Er Tm Yb Lu "
    "Hf Ta W Re Os Ir Pt Au Hg Tl Pb Bi Po At Rn "
    "Fr Ra Ac Th Pa U Np Pu Am Cm Bk Cf Es Fm Md No Lr"
).split()

NAMES = (
    "hydrogen helium lithium beryllium boron carbon nitrogen oxygen "
    "fluorine neon sodium magnesium aluminum silicon phosphorus sulfur "
    "chlorine argon potassium calcium scandium titanium vanadium chromium "
    "manganese iron cobalt nickel copper zinc gallium germanium arsenic "
    "selenium bromine krypton rubidium strontium yttrium zirconium niobium "
    "molybdenum technetium ruthenium rhodium palladium silver cadmium "
    "indium tin antimony tellurium iodine xenon cesium barium lanthanum "
    "cerium praseodymium neodymium promethium samarium europium gadolinium "
    "terbium dysprosium holmium erbium thulium ytterbium lutetium hafnium "
    "tantalum tungsten rhenium osmium iridium platinum gold mercury "
    "thallium lead bismuth polonium astatine radon francium radium "
    "actinium thorium protactinium uranium neptunium plutonium americium "
    "curium berkelium californium einsteinium fermium mendelevium "
    "nobelium lawrencium"
).split()

# standard atomic weights (u); 0 decimals are enough for the species file
MASSES = [
    1.008, 4.0026, 6.94, 9.0122, 10.81, 12.011, 14.007, 15.999, 18.998,
    20.180, 22.990, 24.305, 26.982, 28.085, 30.974, 32.06, 35.45, 39.948,
    39.098, 40.078, 44.956, 47.867, 50.942, 51.996, 54.938, 55.845, 58.933,
    58.693, 63.546, 65.38, 69.723, 72.630, 74.922, 78.971, 79.904, 83.798,
    85.468, 87.62, 88.906, 91.224, 92.906, 95.95, 98.0, 101.07, 102.91,
    106.42, 107.87, 112.41, 114.82, 118.71, 121.76, 127.60, 126.90, 131.29,
    132.91, 137.33, 138.91, 140.12, 140.91, 144.24, 145.0, 150.36, 151.96,
    157.25, 158.93, 162.50, 164.93, 167.26, 168.93, 173.05, 174.97, 178.49,
    180.95, 183.84, 186.21, 190.23, 192.22, 195.08, 196.97, 200.59, 204.38,
    207.2, 208.98, 209.0, 210.0, 222.0, 223.0, 226.0, 227.0, 232.04,
    231.04, 238.03, 237.0, 244.0, 243.0, 247.0, 247.0, 251.0, 252.0,
    257.0, 258.0, 259.0, 262.0,
]

# aufbau (Madelung) filling order
_AUFBAU = [
    (1, 0), (2, 0), (2, 1), (3, 0), (3, 1), (4, 0), (3, 2), (4, 1),
    (5, 0), (4, 2), (5, 1), (6, 0), (4, 3), (5, 2), (6, 1), (7, 0),
    (5, 3), (6, 2), (7, 1),
]

# ground-state configuration exceptions: Z -> list of (n, l, delta_occ)
# applied to the aufbau result (the familiar d/f promotions)
_EXCEPTIONS = {
    24: [(4, 0, -1), (3, 2, +1)],   # Cr
    29: [(4, 0, -1), (3, 2, +1)],   # Cu
    41: [(5, 0, -1), (4, 2, +1)],   # Nb
    42: [(5, 0, -1), (4, 2, +1)],   # Mo
    44: [(5, 0, -1), (4, 2, +1)],   # Ru
    45: [(5, 0, -1), (4, 2, +1)],   # Rh
    46: [(5, 0, -2), (4, 2, +2)],   # Pd
    47: [(5, 0, -1), (4, 2, +1)],   # Ag
    57: [(4, 3, -1), (5, 2, +1)],   # La
    58: [(4, 3, -1), (5, 2, +1)],   # Ce
    64: [(4, 3, -1), (5, 2, +1)],   # Gd
    78: [(6, 0, -1), (5, 2, +1)],   # Pt
    79: [(6, 0, -1), (5, 2, +1)],   # Au
    89: [(5, 3, -1), (6, 2, +1)],   # Ac
    90: [(5, 3, -2), (6, 2, +2)],   # Th
    91: [(5, 3, -1), (6, 2, +1)],   # Pa
    92: [(5, 3, -1), (6, 2, +1)],   # U
    93: [(5, 3, -1), (6, 2, +1)],   # Np
    96: [(5, 3, -1), (6, 2, +1)],   # Cm
    103: [(6, 2, -1), (7, 1, +1)],  # Lr
}


def configuration(zn: int) -> list[tuple[int, int, float]]:
    """Neutral ground-state shells [(n, l, occupancy)] for atomic number zn."""
    if not 1 <= zn <= len(SYMBOLS):
        raise ValueError(f"atomic number out of range: {zn}")
    occ: dict[tuple[int, int], float] = {}
    left = zn
    for (n, l) in _AUFBAU:
        if left <= 0:
            break
        cap = 2 * (2 * l + 1)
        take = min(cap, left)
        occ[(n, l)] = float(take)
        left -= take
    for (n, l, d) in _EXCEPTIONS.get(zn, []):
        occ[(n, l)] = occ.get((n, l), 0.0) + d
        if occ[(n, l)] <= 0:
            del occ[(n, l)]
    shells = sorted(occ.items(), key=lambda kv: (kv[0][0], kv[0][1]))
    return [(n, l, o) for ((n, l), o) in shells]


def _hartree_radial(r: np.ndarray, rho: np.ndarray) -> np.ndarray:
    """v_H(r) of a spherical density (per-volume):
    4 pi [ (1/r) int_0^r rho r'^2 dr' + int_r^inf rho r' dr' ]."""
    from sirius_tpu.core.radial import spline_quadrature_weights

    w = spline_quadrature_weights(r)
    q_in = np.cumsum(w * rho * r * r)
    q_out_rev = np.cumsum((w * rho * r)[::-1])[::-1]
    return 4.0 * np.pi * (q_in / r + (q_out_rev - w * rho * r))


def solve_free_atom(zn: int, xc_names=("XC_LDA_X", "XC_LDA_C_VWN"),
                    rel: str = "none", n_grid: int = 2400,
                    tol: float = 1e-8, max_iter: int = 200) -> dict:
    """Self-consistent spherical (spin-restricted) free atom.

    Returns {r, rho, veff, levels: [(n, l, occ, energy)], energy_tot,
    energy_components}. rho is the per-volume density (integrates to zn
    with the 4 pi r^2 measure). Reference: apps/atoms/atom.cpp scf loop.
    """
    from sirius_tpu.core.radial import Spline, spline_quadrature_weights
    from sirius_tpu.dft.xc import XCFunctional
    from sirius_tpu.lapw.radial_solver import (
        find_bound_state,
        find_bound_state_dirac,
    )

    shells = configuration(zn)
    rmax = 30.0 + zn / 4.0
    r = 1e-6 * (rmax / 1e-6) ** (np.arange(n_grid) / (n_grid - 1.0))
    w = spline_quadrature_weights(r)
    xc = XCFunctional(list(xc_names))

    # initial guess: Slater-screened hydrogenic density
    a = max(zn / 2.0, 1.0)
    rho = zn * a**3 / (8.0 * np.pi) * np.exp(-a * r)
    nrm = 4.0 * np.pi * float(np.sum(w * rho * r * r))
    rho *= zn / nrm

    def xc_eval(rho_):
        if xc.is_gga:
            drho = Spline(r, rho_).derivative(r)
            sigma = np.asarray(drho) ** 2
            out = xc.evaluate(rho_, sigma)
            e = np.asarray(out["e"])
            v = np.asarray(out["v"])
            vs = np.asarray(out["vsigma"])
            # v_xc = de/dn - (1/r^2) d/dr (r^2 * 2 vsigma drho)
            t = 2.0 * vs * np.asarray(drho)
            dt = Spline(r, r * r * t).derivative(r)
            v = v - np.asarray(dt) / np.maximum(r * r, 1e-30)
            return e, v
        out = xc.evaluate(rho_)
        return np.asarray(out["e"]), np.asarray(out["v"])

    beta = 0.5
    e_prev = None
    levels = []
    for it in range(max_iter):
        vh = _hartree_radial(r, rho)
        _, vxc = xc_eval(rho)
        veff = vh + vxc - zn / r
        rho_new = np.zeros_like(r)
        esum = 0.0
        levels = []
        for (n, l, occ) in shells:
            if rel == "dirac":
                e_lvl, u2 = 0.0, np.zeros_like(r)
                for kappa in ([-1] if l == 0 else [l, -l - 1]):
                    deg = 2 * abs(kappa)
                    e, g, f = find_bound_state_dirac(r, veff, n, kappa)
                    e_lvl += deg * e
                    u2 += deg * (g**2 + f**2)
                frac = occ / (2.0 * (2 * l + 1))
                esum += frac * e_lvl
                rho_new += frac * u2 / (4.0 * np.pi)
                levels.append((n, l, occ, e_lvl / (2.0 * (2 * l + 1))))
            else:
                e, u = find_bound_state(
                    r, veff, l, n, rel=rel,
                    e_lo=-0.6 * zn**2 - 10.0,
                )
                esum += occ * e
                rho_new += occ * u**2 / (4.0 * np.pi)
                levels.append((n, l, occ, e))
        # total energy at the OUTPUT density in the INPUT potential:
        # E = sum eps - int rho (vh + vxc) + E_H[rho] + E_xc[rho]
        rint = lambda f: float(np.sum(w * f * r * r)) * 4.0 * np.pi
        vh_n = _hartree_radial(r, rho_new)
        exc_n, vxc_n = xc_eval(rho_new)
        e_h = 0.5 * rint(rho_new * vh_n)
        e_xc = rint(exc_n)  # exc_n is the energy PER VOLUME
        e_tot = (
            esum
            - rint(rho_new * (vh + vxc))
            + e_h
            + e_xc
        )
        de = abs(e_tot - e_prev) if e_prev is not None else np.inf
        e_prev = e_tot
        rho = (1.0 - beta) * rho + beta * rho_new
        if de < tol and it > 3:
            rho = rho_new
            break
    vh = _hartree_radial(r, rho)
    _, vxc = xc_eval(rho)
    veff = vh + vxc - zn / r
    return {
        "r": r,
        "rho": rho,
        "veff": veff,
        "levels": levels,
        "energy_tot": float(e_prev),
        "converged": de < tol,
        "num_iter": it + 1,
    }


def generate_species(symbol: str, xc_names=("XC_LDA_X", "XC_LDA_C_VWN"),
                     rel: str = "none", core_cutoff: float = -10.0,
                     apw_order: int = 2, nrmt: int = 1000,
                     rmt: float = 2.0, apw_enu: float = 0.15) -> dict:
    """Species JSON dict for the FP-LAPW path (reference apps/atoms output):
    levels with energy < core_cutoff (Ha) go to the core string, the rest
    become semicore/valence local orbitals; APW descriptors use a fixed
    default linearization energy. The free-atom density rides along for the
    initial-density superposition."""
    zn = SYMBOLS.index(symbol) + 1
    atom = solve_free_atom(zn, xc_names=xc_names, rel=rel)
    if not atom["converged"]:
        raise RuntimeError(f"free atom {symbol} did not converge")
    spd = "spdfghi"
    core = []
    lo_levels = []
    for (n, l, occ, e) in atom["levels"]:
        if e < core_cutoff:
            core.append(f"{n}{spd[l]}")
        else:
            lo_levels.append((n, l, occ, e))
    # rinf: where the density drops below 1e-20 (reference atomic grids
    # stop near there); keep at least rmt * 2
    r, rho = atom["r"], atom["rho"]
    above = np.nonzero(rho > 1e-20)[0]
    i_inf = int(above[-1]) + 1 if len(above) else len(r)
    rinf = float(max(r[min(i_inf, len(r) - 1)], 2.0 * rmt))
    keep = r <= rinf

    valence = [{
        "basis": [
            {"enu": apw_enu, "dme": d, "auto": 0} for d in range(apw_order)
        ]
    }]
    lo = []
    for (n, l, occ, e) in lo_levels:
        lo.append({
            "l": l,
            "basis": [
                {"n": n, "enu": round(float(e), 6), "dme": 0, "auto": 1},
                {"n": n, "enu": round(float(e), 6), "dme": 1, "auto": 1},
            ],
        })
    return {
        "name": NAMES[zn - 1],
        "symbol": symbol,
        "number": zn,
        "mass": MASSES[zn - 1],
        "rmin": 1e-5,
        "rmt": float(rmt),
        "nrmt": int(nrmt),
        "rinf": rinf,
        "core": "".join(core),
        "valence": valence,
        "lo": lo,
        "free_atom": {
            "density": [float(x) for x in rho[keep]],
            "radial_grid": [float(x) for x in r[keep]],
        },
    }


def main(argv=None) -> int:
    """CLI: sirius-atom --symbol Fe [--xc ...] [--rel dirac] [-o Fe.json]
    (the reference `atom` mini-app)."""
    import argparse

    p = argparse.ArgumentParser(
        prog="sirius-atom",
        description="Free-atom solver / FP species generator (sirius_tpu)",
    )
    p.add_argument("--symbol", required=True, help="element symbol, e.g. Fe")
    p.add_argument(
        "--xc", default="XC_LDA_X,XC_LDA_C_VWN",
        help="comma-separated XC functional names",
    )
    p.add_argument(
        "--rel", default="none",
        choices=["none", "zora", "iora", "koelling_harmon", "dirac"],
    )
    p.add_argument("--core-cutoff", type=float, default=-10.0,
                   help="levels below this energy (Ha) become core states")
    p.add_argument("--apw-order", type=int, default=2, choices=[1, 2],
                   help="1 = APW (value matching), 2 = LAPW (u, udot)")
    p.add_argument("--rmt", type=float, default=2.0)
    p.add_argument("--nrmt", type=int, default=1000)
    p.add_argument("-o", "--output", default=None,
                   help="output file (default <symbol>.json)")
    args = p.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")
    sp = generate_species(
        args.symbol, xc_names=args.xc.split(","), rel=args.rel,
        core_cutoff=args.core_cutoff, apw_order=args.apw_order,
        rmt=args.rmt, nrmt=args.nrmt,
    )
    out = args.output or f"{args.symbol}.json"
    with open(out, "w") as f:
        json.dump(sp, f, indent=1)
    print(f"{args.symbol}: core='{sp['core']}', {len(sp['lo'])} lo channels, "
          f"rinf={sp['rinf']:.3f} -> {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
