"""Lint engine: file/project indexing, jit-reachability, suppression
comments, findings, and the checked-in baseline.

The engine is pure ``ast``/``tokenize`` — it never imports the modules it
analyses (linting must work without jax installed and must not trigger
backend initialization). Rules are project-scoped: each rule class gets
the whole :class:`ProjectIndex` so cross-file analyses (the lock graph,
the jit-reachability closure, registry lookups) are first-class rather
than bolted on.

Suppression grammar (comments anywhere on the offending line)::

    x = np.asarray(y)  # sirius-lint: disable=jit-numpy-call
    # sirius-lint: disable-file=lock-order-cycle   (anywhere in the file)
    y = bad()          # sirius-lint: disable=*    (every rule, this line)

Baseline: findings are fingerprinted by ``(rule, enclosing qualname,
whitespace-normalized source-line text)`` — stable across unrelated
edits that shift line numbers AND across file renames (the path is
advisory metadata on the baseline entry, not part of the key) — and
compared as multisets, so CI fails only when a fingerprint's count
*grows* (a genuinely new violation), never on pre-existing, justified
ones. ``write_baseline`` migrates pre-rename baselines in place:
justifications are carried over by fingerprint first, then by
``(rule, normalized text)`` for entries whose fingerprint scheme (or
enclosing file) changed.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import os
import re
import tokenize

_SUPPRESS_RE = re.compile(
    r"#\s*sirius-lint:\s*(disable(?:-file)?)\s*=\s*([A-Za-z0-9_\-*,\s]+)")


# ---------------------------------------------------------------------------
# findings


def normalize_text(text: str) -> str:
    """Whitespace-collapsed source line: the fingerprint's text key."""
    return " ".join(text.split())


@dataclasses.dataclass
class Finding:
    rule: str
    path: str  # posix relpath from the scan root (advisory, not keyed)
    line: int
    col: int
    message: str
    text: str = ""  # stripped source line (fingerprint input)
    qualname: str = "<module>"  # enclosing function/method qualname

    @property
    def fingerprint(self) -> str:
        """Keyed on (rule, enclosing qualname, normalized text) so a file
        rename — or a pure reformat — does not orphan baseline entries;
        the path rides along as advisory metadata only."""
        h = hashlib.sha1(
            f"{self.rule}|{self.qualname}|{normalize_text(self.text)}"
            .encode()).hexdigest()
        return h[:16]

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "col": self.col, "message": self.message, "text": self.text,
            "qualname": self.qualname, "fingerprint": self.fingerprint,
        }

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule}] {self.message}")


# ---------------------------------------------------------------------------
# AST helpers shared by the rule modules


def dotted_name(e: ast.AST) -> str | None:
    """'a.b.c' for Name/Attribute chains, None for anything else."""
    parts: list[str] = []
    while isinstance(e, ast.Attribute):
        parts.append(e.attr)
        e = e.value
    if isinstance(e, ast.Name):
        parts.append(e.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str | None:
    return dotted_name(call.func)


def assigned_names(target: ast.AST) -> list[str]:
    """Plain Name identifiers bound by an assignment target."""
    out: list[str] = []
    for n in ast.walk(target):
        if isinstance(n, ast.Name) and isinstance(
                n.ctx, (ast.Store, ast.Del)):
            out.append(n.id)
    return out


# ---------------------------------------------------------------------------
# file / project indexing


class FileContext:
    """One parsed source file plus its suppression map."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.line_suppressions: dict[int, set[str]] = {}
        self.file_suppressions: set[str] = set()
        # every suppression token as written: (comment line, rule, file?)
        # — the stale-suppression audit diffs this against what fired
        self.suppression_records: list[tuple[int, str, bool]] = []
        self._scan_suppressions()

    def _scan_suppressions(self) -> None:
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.source).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
                file_level = m.group(1) == "disable-file"
                for r in sorted(rules):
                    self.suppression_records.append(
                        (tok.start[0], r, file_level))
                if file_level:
                    self.file_suppressions |= rules
                else:
                    self.line_suppressions.setdefault(
                        tok.start[0], set()).update(rules)
        except tokenize.TokenError:
            pass  # truncated file: lint what parsed, skip comment scan

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_suppressions or "*" in self.file_suppressions:
            return True
        on_line = self.line_suppressions.get(line, ())
        return rule in on_line or "*" in on_line

    def matching_suppressions(self, rule: str, line: int):
        """The suppression records a (rule, line) finding is silenced by,
        as (comment_line, rule_token, file_level) keys."""
        out = []
        for tok in (rule, "*"):
            if tok in self.file_suppressions:
                out.extend(r for r in self.suppression_records
                           if r[1] == tok and r[2])
            if tok in self.line_suppressions.get(line, ()):
                out.append((line, tok, False))
        return out

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""


class FunctionInfo:
    """One function/method (or seeded lambda) in the project index."""

    def __init__(self, module: "ModuleInfo", qualname: str, node: ast.AST,
                 cls: str | None = None):
        self.module = module
        self.qualname = qualname  # "func" | "Class.method" | "<lambda@N>"
        self.node = node
        self.cls = cls
        self.jit_seed = False
        self.jit_kwargs: dict[str, ast.AST] = {}  # static/donate argnums

    @property
    def key(self) -> tuple[str, str]:
        return (self.module.name, self.qualname)

    def __repr__(self) -> str:
        return f"<fn {self.module.name}:{self.qualname}>"


class ModuleInfo:
    def __init__(self, name: str, fctx: FileContext):
        self.name = name
        self.fctx = fctx
        self.functions: dict[str, FunctionInfo] = {}
        self.imports: dict[str, str] = {}  # local alias -> dotted target
        self.classes: dict[str, ast.ClassDef] = {}


_JIT_WRAPPERS = {
    "jax.jit", "jit", "jax.pmap", "pmap",
    "eqx.filter_jit", "equinox.filter_jit", "filter_jit",
}
_PARTIAL = {"partial", "functools.partial"}
# higher-order ops that trace their function-valued arguments even when
# called outside an enclosing jit
_TRACING_HOFS = {
    "jax.lax.scan", "lax.scan", "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.fori_loop", "lax.fori_loop", "jax.lax.cond", "lax.cond",
    "jax.lax.switch", "lax.switch", "jax.lax.map", "lax.map",
    "jax.lax.associative_scan", "lax.associative_scan",
    "jax.checkpoint", "jax.remat", "jax.vmap", "jax.grad",
    "jax.value_and_grad",
}


class ProjectIndex:
    """Modules, functions, imports, and the jit-reachability closure."""

    def __init__(self, root: str, paths: list[str]):
        self.root = os.path.abspath(root)
        self.modules: dict[str, ModuleInfo] = {}
        self.by_relpath: dict[str, ModuleInfo] = {}
        self.files: list[FileContext] = []
        self.errors: list[str] = []
        for p in paths:
            self._index_file(p)
        self._jit_reachable: set[tuple[str, str]] | None = None
        self._lambda_counter = 0

    # -- indexing ----------------------------------------------------------

    def _module_name(self, relpath: str) -> str:
        mod = relpath.replace(os.sep, "/")
        if mod.endswith(".py"):
            mod = mod[:-3]
        if mod.endswith("/__init__"):
            mod = mod[: -len("/__init__")]
        return mod.replace("/", ".")

    def _index_file(self, path: str) -> None:
        relpath = os.path.relpath(os.path.abspath(path), self.root)
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
            fctx = FileContext(path, relpath, source)
        except (OSError, SyntaxError, ValueError) as e:
            self.errors.append(f"{relpath}: {type(e).__name__}: {e}")
            return
        mi = ModuleInfo(self._module_name(relpath), fctx)
        self.modules[mi.name] = mi
        self.by_relpath[fctx.relpath] = mi
        self.files.append(fctx)
        pkg = mi.name.rsplit(".", 1)[0] if "." in mi.name else ""
        for node in ast.walk(fctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mi.imports[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    parts = pkg.split(".") if pkg else []
                    parts = parts[: len(parts) - (node.level - 1)]
                    base = ".".join(parts + ([node.module]
                                             if node.module else []))
                for a in node.names:
                    if a.name == "*":
                        continue
                    mi.imports[a.asname or a.name] = (
                        f"{base}.{a.name}" if base else a.name)
        for node in fctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mi.functions[node.name] = FunctionInfo(mi, node.name, node)
            elif isinstance(node, ast.ClassDef):
                mi.classes[node.name] = node
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        q = f"{node.name}.{sub.name}"
                        mi.functions[q] = FunctionInfo(
                            mi, q, sub, cls=node.name)

    # -- call/seed resolution ---------------------------------------------

    def _resolve_call(self, mi: ModuleInfo, cls: str | None,
                      name: str) -> list[FunctionInfo]:
        """FunctionInfo candidates a dotted call name may refer to."""
        out: list[FunctionInfo] = []
        if name.startswith("self.") and cls:
            q = f"{cls}.{name[5:]}"
            if q in mi.functions:
                out.append(mi.functions[q])
            return out
        if "." not in name:
            if name in mi.functions:
                out.append(mi.functions[name])
            elif name in mi.imports:
                tgt = mi.imports[name]
                if "." in tgt:
                    m, f = tgt.rsplit(".", 1)
                    if m in self.modules and f in self.modules[m].functions:
                        out.append(self.modules[m].functions[f])
            return out
        head, rest = name.split(".", 1)
        base = mi.imports.get(head, head)
        full = f"{base}.{rest}"
        # longest module prefix wins: "pkg.mod.Class.method" or "pkg.mod.fn"
        parts = full.split(".")
        for i in range(len(parts) - 1, 0, -1):
            m = ".".join(parts[:i])
            if m in self.modules:
                f = ".".join(parts[i:])
                if f in self.modules[m].functions:
                    out.append(self.modules[m].functions[f])
                break
        return out

    def _seed_target(self, mi: ModuleInfo, cls: str | None, arg: ast.AST,
                     enclosing: "FunctionInfo | None" = None,
                     ) -> list[FunctionInfo]:
        if isinstance(arg, ast.Lambda):
            self._lambda_counter += 1
            q = f"<lambda@{arg.lineno}#{self._lambda_counter}>"
            fi = FunctionInfo(mi, q, arg, cls=cls)
            mi.functions[q] = fi
            return [fi]
        if isinstance(arg, ast.Call):
            # unwrap jit(partial(f, ...)) / jit(shard_map(f, ...)) /
            # jit(checkpoint(f)) down to the function they wrap
            cn = call_name(arg) or ""
            if (cn in _PARTIAL or cn.split(".")[-1] in (
                    "shard_map", "checkpoint", "remat", "vmap", "pmap")
                    ) and arg.args:
                return self._seed_target(mi, cls, arg.args[0], enclosing)
            return []
        d = dotted_name(arg)
        if not d:
            return []
        out = self._resolve_call(mi, cls, d)
        if out or enclosing is None or "." in d:
            return out
        # a nested def: jax.jit(run) where run is local to `enclosing`
        for node in ast.walk(enclosing.node):
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name == d and node is not enclosing.node):
                q = f"{enclosing.qualname}.<locals>.{d}@{node.lineno}"
                fi = mi.functions.get(q)
                if fi is None:
                    fi = FunctionInfo(mi, q, node, cls=cls)
                    mi.functions[q] = fi
                return [fi]
        return out

    def _mark_seeds(self) -> None:
        for mi in self.modules.values():
            for fi in list(mi.functions.values()):
                node = fi.node
                for dec in getattr(node, "decorator_list", []):
                    d = dotted_name(dec)
                    if d in _JIT_WRAPPERS:
                        fi.jit_seed = True
                    elif isinstance(dec, ast.Call):
                        dc = call_name(dec)
                        if dc in _JIT_WRAPPERS:
                            fi.jit_seed = True
                            fi.jit_kwargs = {
                                k.arg: k.value for k in dec.keywords if k.arg}
                        elif dc in _PARTIAL and dec.args and dotted_name(
                                dec.args[0]) in _JIT_WRAPPERS:
                            fi.jit_seed = True
                            fi.jit_kwargs = {
                                k.arg: k.value for k in dec.keywords if k.arg}
            # expression-form seeds: jax.jit(f, ...) / lax.scan(body, ...)
            for fi in list(mi.functions.values()):
                for call in [n for n in ast.walk(fi.node)
                             if isinstance(n, ast.Call)]:
                    cn = call_name(call)
                    if cn in _JIT_WRAPPERS and call.args:
                        for tgt in self._seed_target(mi, fi.cls,
                                                     call.args[0], fi):
                            tgt.jit_seed = True
                            tgt.jit_kwargs.update({
                                k.arg: k.value
                                for k in call.keywords if k.arg})
                    elif cn in _TRACING_HOFS:
                        for a in call.args:
                            for tgt in self._seed_target(mi, fi.cls, a, fi):
                                tgt.jit_seed = True

    def function_calls(self, fi: FunctionInfo) -> list[FunctionInfo]:
        out: list[FunctionInfo] = []
        for call in [n for n in ast.walk(fi.node)
                     if isinstance(n, ast.Call)]:
            d = call_name(call)
            if d:
                out.extend(self._resolve_call(fi.module, fi.cls, d))
        return out

    def jit_reachable(self) -> set[tuple[str, str]]:
        """Keys of every function in the transitive closure of the jit
        seeds over the resolved project call graph."""
        if self._jit_reachable is not None:
            return self._jit_reachable
        self._mark_seeds()
        seen: set[tuple[str, str]] = set()
        frontier = [fi for mi in self.modules.values()
                    for fi in mi.functions.values() if fi.jit_seed]
        while frontier:
            fi = frontier.pop()
            if fi.key in seen:
                continue
            seen.add(fi.key)
            frontier.extend(self.function_calls(fi))
        self._jit_reachable = seen
        return seen

    def iter_functions(self):
        for mi in self.modules.values():
            yield from mi.functions.values()

    # -- findings ----------------------------------------------------------

    def qualname_at(self, fctx: FileContext, line: int) -> str:
        """Qualname of the innermost indexed function enclosing ``line``
        (``<module>`` for top-level code) — the rename-stable fingerprint
        anchor."""
        mi = self.by_relpath.get(fctx.relpath)
        if mi is None:
            return "<module>"
        best = None
        for fi in mi.functions.values():
            lo = getattr(fi.node, "lineno", None)
            hi = getattr(fi.node, "end_lineno", None)
            if lo is None or hi is None or not (lo <= line <= hi):
                continue
            if best is None or lo > best[0]:
                best = (lo, fi.qualname)
        return best[1] if best else "<module>"

    def finding(self, rule: str, fi_or_fctx, node: ast.AST | None,
                message: str) -> Finding:
        fctx = (fi_or_fctx.module.fctx
                if isinstance(fi_or_fctx, FunctionInfo) else fi_or_fctx)
        line = getattr(node, "lineno", 1) if node is not None else 1
        col = getattr(node, "col_offset", 0) if node is not None else 0
        if isinstance(fi_or_fctx, FunctionInfo):
            qualname = fi_or_fctx.qualname
        else:
            qualname = self.qualname_at(fctx, line)
        return Finding(rule=rule, path=fctx.relpath, line=line, col=col,
                       message=message, text=fctx.line_text(line),
                       qualname=qualname)


# ---------------------------------------------------------------------------
# engine


def all_rules() -> list:
    from sirius_tpu.analysis import (
        compilerules,
        jaxrules,
        lockrules,
        registryrules,
        shardrules,
        transferrules,
    )

    return (list(jaxrules.RULES) + list(lockrules.RULES)
            + list(registryrules.RULES) + list(compilerules.RULES)
            + list(transferrules.RULES) + list(shardrules.RULES))


DEFAULT_SCAN = ("sirius_tpu", "tools", "tests", "bench.py")
_SKIP_DIRS = {"__pycache__", ".git", "csrc", ".github"}


def collect_files(root: str, targets=DEFAULT_SCAN) -> list[str]:
    out: list[str] = []
    for t in targets:
        p = t if os.path.isabs(t) else os.path.join(root, t)
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for f in sorted(filenames):
                if f.endswith(".py"):
                    out.append(os.path.join(dirpath, f))
    return out


class LintEngine:
    def __init__(self, root: str, paths: list[str] | None = None,
                 rules=None, registry=None):
        self.root = os.path.abspath(root)
        self.paths = paths if paths is not None else collect_files(self.root)
        self.project = ProjectIndex(self.root, self.paths)
        self.rules = rules if rules is not None else all_rules()
        self.registry = registry  # RegistryConfig override (tests)
        self.suppressed_count = 0
        # (relpath, comment_line, rule_token, file_level) records that
        # actually silenced a finding in the last run()
        self.used_suppressions: set[tuple] = set()
        self._ran = False

    def run(self) -> list[Finding]:
        findings: list[Finding] = []
        by_path = {f.relpath: f for f in self.project.files}
        seen: set[tuple] = set()  # lambdas re-walk their parent's lines
        for rule in self.rules:
            kwargs = {}
            if self.registry is not None and getattr(
                    rule, "wants_registry", False):
                kwargs["registry"] = self.registry
            for f in rule().run(self.project, **kwargs):
                key = (f.rule, f.path, f.line, f.col, f.message)
                if key in seen:
                    continue
                seen.add(key)
                fctx = by_path.get(f.path)
                if fctx is not None and fctx.suppressed(f.rule, f.line):
                    self.suppressed_count += 1
                    for rec in fctx.matching_suppressions(f.rule, f.line):
                        self.used_suppressions.add((fctx.relpath, *rec))
                    continue
                findings.append(f)
        findings.sort(key=lambda f: (f.path, f.line, f.rule))
        self._ran = True
        return findings

    def stale_suppressions(self) -> list[dict]:
        """Suppression comments that silenced nothing in the last run():
        either the violation was fixed (the comment is dead weight hiding
        future regressions) or the rule name is a typo and the comment
        never worked. Only meaningful after run() with the full rule set —
        the CLI guards the partial --rules case."""
        assert self._ran, "run() first"
        known = {r.name for r in self.rules}
        out = []
        for fctx in self.project.files:
            for line, rule, file_level in fctx.suppression_records:
                key = (fctx.relpath, line, rule, file_level)
                if key in self.used_suppressions:
                    continue
                reason = ("never fired" if rule == "*" or rule in known
                          else "unknown rule")
                out.append({
                    "path": fctx.relpath, "line": line, "rule": rule,
                    "file_level": file_level, "reason": reason,
                    "text": fctx.line_text(line),
                })
        out.sort(key=lambda e: (e["path"], e["line"], e["rule"]))
        return out


# ---------------------------------------------------------------------------
# baseline


def load_baseline(path: str) -> dict:
    """fingerprint -> {count, rule, path, text, justification}."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    return {e["fingerprint"]: e for e in data.get("findings", [])}


def write_baseline(path: str, findings: list[Finding],
                   old: dict | None = None) -> dict:
    """Aggregate findings into a baseline file, preserving justifications
    from the previous baseline for fingerprints that persist. Entries
    whose fingerprint changed (scheme migration, function rename) fall
    back to a (rule, normalized text) match so justifications survive."""
    old = old or {}
    by_text = {(e.get("rule"), normalize_text(e.get("text", ""))): e
               for e in old.values() if e.get("justification")}

    def _justification(f: Finding) -> str:
        hit = old.get(f.fingerprint)
        if hit and hit.get("justification"):
            return hit["justification"]
        hit = by_text.get((f.rule, normalize_text(f.text)))
        return hit["justification"] if hit else ""

    agg: dict[str, dict] = {}
    for f in findings:
        e = agg.setdefault(f.fingerprint, {
            "fingerprint": f.fingerprint, "rule": f.rule, "path": f.path,
            "qualname": f.qualname, "text": f.text, "count": 0,
            "justification": _justification(f),
        })
        e["count"] += 1
    data = {
        "version": 1,
        "comment": ("sirius-lint baseline: pre-existing, justified findings."
                    " CI fails only when a fingerprint's count grows."),
        "findings": sorted(agg.values(),
                           key=lambda e: (e["path"], e["rule"], e["text"])),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=1, sort_keys=False)
        fh.write("\n")
    return agg


def new_findings(findings: list[Finding], baseline: dict) -> list[Finding]:
    """Findings whose fingerprint count exceeds the baselined count."""
    budget = {fp: e.get("count", 0) for fp, e in baseline.items()}
    out: list[Finding] = []
    for f in findings:  # engine output is sorted: excess = later lines
        if budget.get(f.fingerprint, 0) > 0:
            budget[f.fingerprint] -= 1
        else:
            out.append(f)
    return out
