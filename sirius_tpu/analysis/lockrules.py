"""Concurrency rules for the threaded ``serve/`` modules.

A static lock-acquisition model is built from ``with self._lock:``
nesting plus interprocedural call edges:

- **Lock identity** is ``(Class, attribute)`` (or ``(module, name)`` for
  module-level locks), with ``threading.Condition(self._lock)`` aliased
  to the lock it wraps — JobQueue's ``_not_empty``/``_not_full`` are the
  *same* lock as ``_lock``.
- **Held sets** propagate through resolved calls: ``self.method()``,
  ``self.attr.method()`` (attribute types from ``__init__`` assignments,
  parameter annotations, and a class-name suffix heuristic),
  parameter/local calls (``job._transition()`` via the ``job: Job``
  annotation), and callback attributes (``job._on_terminal = self.x``).
- **Thread roots** are ``threading.Thread(target=...)`` methods,
  self-method callback arguments (``health_fn=self._health``), callback
  attribute assignments, and every public method (the external caller's
  thread).

Three rules read the model: ``lock-order-cycle`` (a cycle in the
acquisition graph, or re-acquiring a held non-reentrant Lock — both
potential deadlocks), ``unlocked-shared-write`` (a ``self.attr`` write
outside ``__init__`` with no lock held on some path, for an attribute
accessed from two or more distinct roots), and ``locked-suffix-call``
(a ``*_locked``-named method invoked with no lock held).

Known limitation: ``Condition.wait()`` releasing the lock inside a
``with`` block is not modelled; held sets are an over-approximation.
"""

from __future__ import annotations

import ast

from sirius_tpu.analysis.core import (
    FunctionInfo,
    ProjectIndex,
    call_name,
    dotted_name,
)

# path fragments whose modules are in lock-analysis scope: the serving
# layer plus the fleet federation built on it (ISSUE 19) — fleet locks
# nest under serve/queue locks, so the order graph must span both
SCOPE_SUBSTRS = ("serve/", "fleet/")


def _in_scope(relpath: str) -> bool:
    return any(s in relpath for s in SCOPE_SUBSTRS)

_LOCK_CTORS = {"threading.Lock": "lock", "Lock": "lock",
               "threading.RLock": "rlock", "RLock": "rlock"}
_COND_CTORS = {"threading.Condition", "Condition"}
_NONLOCK_SYNC = {"Event", "Semaphore", "Barrier"}  # not mutual exclusion


class _ClassModel:
    def __init__(self, mi, cdef: ast.ClassDef):
        self.mi = mi
        self.cdef = cdef
        self.key = f"{mi.name}.{cdef.name}"
        self.locks: dict[str, str] = {}       # attr -> canonical attr
        self.lock_kinds: dict[str, str] = {}  # canonical attr -> lock|rlock
        self.attr_types: dict[str, str] = {}  # attr -> class key
        self._scan_init()

    def lock_id(self, attr: str):
        canon = self.locks.get(attr)
        if canon is None:
            return None
        return (self.key, canon)

    def _scan_init(self) -> None:
        init = None
        for sub in self.cdef.body:
            if isinstance(sub, ast.FunctionDef) and sub.name == "__init__":
                init = sub
                break
        if init is None:
            return
        ann: dict[str, ast.AST] = {
            a.arg: a.annotation for a in init.args.args if a.annotation}
        for node in ast.walk(init):
            target = value = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign):
                target, value = node.target, node.value
            if not (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                continue
            attr = target.attr
            if isinstance(value, ast.Call):
                d = call_name(value)
                if d in _LOCK_CTORS:
                    self.locks[attr] = attr
                    self.lock_kinds[attr] = _LOCK_CTORS[d]
                    continue
                if d in _COND_CTORS:
                    arg = dotted_name(value.args[0]) if value.args else None
                    if arg and arg.startswith("self."):
                        wrapped = arg[5:]
                        self.locks[attr] = self.locks.get(wrapped, wrapped)
                    else:  # Condition() owns a private Lock
                        self.locks[attr] = attr
                        self.lock_kinds[attr] = "rlock"
                    continue
            if isinstance(node, ast.AnnAssign) and node.annotation is not None:
                self.attr_types.setdefault(attr, ("__ann__", node.annotation))
            elif isinstance(value, ast.Name) and value.id in ann:
                self.attr_types.setdefault(
                    attr, ("__ann__", ann[value.id]))
            elif isinstance(value, ast.Call):
                d = call_name(value)
                if d:
                    self.attr_types.setdefault(attr, ("__ctor__", d))


class _Model:
    """All serve-scope classes, locks, roots, and callback registry."""

    def __init__(self, project: ProjectIndex):
        self.project = project
        self.modules = [mi for mi in project.modules.values()
                        if _in_scope(mi.fctx.relpath)]
        self.classes: dict[str, _ClassModel] = {}
        self.module_locks: dict[tuple[str, str], str] = {}  # id -> kind
        for mi in self.modules:
            for cname, cdef in mi.classes.items():
                cm = _ClassModel(mi, cdef)
                self.classes[cm.key] = cm
            for node in mi.fctx.tree.body:
                if (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and isinstance(node.value, ast.Call)):
                    d = call_name(node.value)
                    if d in _LOCK_CTORS:
                        lid = (mi.name, node.targets[0].id)
                        self.module_locks[lid] = _LOCK_CTORS[d]
        # callback registry + roots
        self.callback_attrs: dict[str, list[FunctionInfo]] = {}
        self.roots: list[tuple[str, FunctionInfo]] = []
        self._find_roots()

    # -- type resolution ---------------------------------------------------

    def _resolve_class(self, mi, name: str | None):
        if not name:
            return None
        tgt = mi.imports.get(name.split(".")[0], None)
        candidates = [name]
        if tgt:
            candidates.append(tgt + name[len(name.split(".")[0]):])
        for cand in candidates:
            tail = cand.split(".")[-1]
            for cm in self.classes.values():
                if cm.cdef.name == tail:
                    return cm
        return None

    def _annotation_class(self, mi, node: ast.AST):
        if isinstance(node, ast.BinOp):  # X | None
            return (self._annotation_class(mi, node.left)
                    or self._annotation_class(mi, node.right))
        if isinstance(node, ast.Subscript):  # Optional[X]
            return self._annotation_class(mi, node.slice)
        return self._resolve_class(mi, dotted_name(node))

    def _heuristic_class(self, name: str):
        if len(name) < 3:
            return None
        low = name.lower().replace("_", "")
        for cm in self.classes.values():
            if cm.cdef.name.lower().endswith(low):
                return cm
        return None

    def attr_class(self, cm: _ClassModel, attr: str):
        t = cm.attr_types.get(attr)
        if t is not None:
            kind, val = t
            got = (self._annotation_class(cm.mi, val) if kind == "__ann__"
                   else self._resolve_class(cm.mi, val))
            if got is not None:
                return got
        return self._heuristic_class(attr)

    def var_class(self, fi: FunctionInfo, name: str):
        node = fi.node
        args = getattr(node, "args", None)
        if args is not None:
            for a in list(args.args) + list(args.kwonlyargs):
                if a.arg == name and a.annotation is not None:
                    got = self._annotation_class(fi.module, a.annotation)
                    if got is not None:
                        return got
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                    and isinstance(sub.targets[0], ast.Name)
                    and sub.targets[0].id == name
                    and isinstance(sub.value, ast.Call)):
                got = self._resolve_class(fi.module, call_name(sub.value))
                if got is not None:
                    return got
        return self._heuristic_class(name)

    def class_method(self, cm: _ClassModel, name: str):
        return cm.mi.functions.get(f"{cm.cdef.name}.{name}")

    def class_of(self, fi: FunctionInfo):
        if fi.cls is None:
            return None
        return self.classes.get(f"{fi.module.name}.{fi.cls}")

    # -- roots -------------------------------------------------------------

    def _self_method(self, fi: FunctionInfo, value: ast.AST):
        """FunctionInfo when ``value`` is ``self.M`` / ``self.a.M`` naming
        a method in scope."""
        d = dotted_name(value)
        if not d or not d.startswith("self."):
            return None
        parts = d.split(".")
        cm = self.class_of(fi)
        if cm is None:
            return None
        if len(parts) == 2:
            return self.class_method(cm, parts[1])
        target = self.attr_class(cm, parts[1])
        if target is not None:
            return self.class_method(target, parts[-1])
        return None

    def _find_roots(self) -> None:
        seen: set[tuple[str, str]] = set()

        def add(label: str, fi: FunctionInfo | None):
            if fi is not None and (label, str(fi.key)) not in seen:
                seen.add((label, str(fi.key)))
                self.roots.append((label, fi))

        for mi in self.modules:
            for fi in mi.functions.values():
                tail = fi.qualname.rsplit(".", 1)[-1]
                if not tail.startswith("_") and tail != "__init__":
                    add("public", fi)
        for mi in self.modules:
            for fi in list(mi.functions.values()):
                for node in ast.walk(fi.node):
                    if isinstance(node, ast.Call):
                        is_thread = (call_name(node) or "").split(
                            ".")[-1] == "Thread"
                        for k in node.keywords:
                            tgt = self._self_method(fi, k.value)
                            if tgt is None:
                                continue
                            if k.arg == "target":
                                add(f"thread:{tgt.qualname}", tgt)
                            elif is_thread is False:
                                add(f"callback:{tgt.qualname}", tgt)
                    elif (isinstance(node, ast.Assign)
                          and len(node.targets) == 1
                          and isinstance(node.targets[0], ast.Attribute)):
                        tgt = self._self_method(fi, node.value)
                        if tgt is not None:
                            attr = node.targets[0].attr
                            self.callback_attrs.setdefault(
                                attr, []).append(tgt)
                            add(f"callback:{tgt.qualname}", tgt)


class _Analysis:
    """One interprocedural walk from every root, recording lock edges,
    attribute accesses, and ``*_locked`` call discipline."""

    def __init__(self, model: _Model):
        self.m = model
        self.edges: dict[tuple, tuple] = {}      # (l1,l2) -> (fi, node)
        self.reacquire: list[tuple] = []          # (lid, fi, node)
        self.writes: dict[tuple, list] = {}       # (cls,attr) -> records
        self.access_roots: dict[tuple, set] = {}  # (cls,attr) -> roots
        self.unlocked_calls: list[tuple] = []     # (name, fi, node)
        self._memo: set[tuple] = set()
        self._stack: list[tuple] = []
        for label, fi in model.roots:
            self.run(fi, (), label)

    # -- lock identities ---------------------------------------------------

    def _lock_id(self, fi: FunctionInfo, expr: ast.AST):
        d = dotted_name(expr)
        if not d:
            return None, None
        if d.startswith("self."):
            cm = self.m.class_of(fi)
            if cm is None:
                return None, None
            attr = d.split(".")[1]
            lid = cm.lock_id(attr)
            if lid is None:
                return None, None
            kind = cm.lock_kinds.get(lid[1], "lock")
            return lid, kind
        if "." not in d:
            lid = (fi.module.name, d)
            if lid in self.m.module_locks:
                return lid, self.m.module_locks[lid]
        return None, None

    def _acquire(self, fi, node, lid, kind, held):
        for h in held:
            if h == lid:
                if kind == "lock":
                    self.reacquire.append((lid, fi, node))
                return held  # reentrant: no self-edge
        for h in held:
            self.edges.setdefault((h, lid), (fi, node))
        return held + (lid,)

    # -- call resolution ---------------------------------------------------

    def _targets(self, fi: FunctionInfo, call: ast.Call):
        d = call_name(call)
        if not d:
            return []
        parts = d.split(".")
        out = []
        if parts[0] == "self":
            cm = self.m.class_of(fi)
            if cm is not None:
                if len(parts) == 2:
                    tgt = self.m.class_method(cm, parts[1])
                    if tgt is not None:
                        return [tgt]
                    # callback attribute: self._on_terminal(...)
                    return list(self.m.callback_attrs.get(parts[1], []))
                target = self.m.attr_class(cm, parts[1])
                if target is not None:
                    tgt = self.m.class_method(target, parts[2])
                    if tgt is not None:
                        return [tgt]
            return out
        if len(parts) >= 2:
            vcm = self.m.var_class(fi, parts[0])
            if vcm is not None:
                tgt = self.m.class_method(vcm, parts[1])
                if tgt is not None:
                    return [tgt]
        # plain / imported function
        for tgt in self.m.project._resolve_call(fi.module, fi.cls, d):
            if _in_scope(tgt.module.fctx.relpath):
                out.append(tgt)
        return out

    # -- the walk ----------------------------------------------------------

    def run(self, fi: FunctionInfo, held: tuple, root: str) -> None:
        key = (str(fi.key), held, root)
        if key in self._memo or key in self._stack:
            return
        self._stack.append(key)
        try:
            body = getattr(fi.node, "body", None)
            if isinstance(body, list):
                self._block(fi, body, held, root)
        finally:
            self._stack.pop()
            self._memo.add(key)

    def _block(self, fi, stmts, held, root):
        for s in stmts:
            self._stmt(fi, s, held, root)

    def _stmt(self, fi, node, held, root):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in node.items:
                self._expr(fi, item.context_expr, new_held, root)
                if isinstance(item.context_expr, ast.Call):
                    continue  # with ctxmgr(...) — not a bare lock
                lid, kind = self._lock_id(fi, item.context_expr)
                if lid is not None:
                    new_held = self._acquire(fi, node, lid, kind, new_held)
            self._block(fi, node.body, new_held, root)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._block(fi, node.body, (), root)  # closure: runs later
            return
        header = {
            ast.If: ["test"], ast.While: ["test"],
            ast.For: ["iter", "target"], ast.AsyncFor: ["iter", "target"],
        }.get(type(node))
        if header is not None:
            for attr in header:
                self._expr(fi, getattr(node, attr), held, root)
            self._block(fi, node.body, held, root)
            self._block(fi, getattr(node, "orelse", []) or [], held, root)
            return
        if isinstance(node, ast.Try):
            self._block(fi, node.body, held, root)
            for h in node.handlers:
                self._block(fi, h.body, held, root)
            self._block(fi, node.orelse, held, root)
            self._block(fi, node.finalbody, held, root)
            return
        self._expr(fi, node, held, root)

    def _expr(self, fi, node, held, root):
        if node is None:
            return
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                self._call(fi, n, held, root)
            elif isinstance(n, ast.Attribute):
                self._attr_access(fi, n, held, root)

    def _call(self, fi, call, held, root):
        d = call_name(call)
        if d:
            last = d.split(".")[-1]
            if last == "acquire":
                lid, kind = self._lock_id(fi, call.func.value)
                if lid is not None:
                    self._acquire(fi, call, lid, kind, held)
                    return
            if last.endswith("_locked") and not held:
                self.unlocked_calls.append((d, fi, call))
        for tgt in self._targets(fi, call):
            self.run(tgt, held, root)

    def _attr_access(self, fi, node: ast.Attribute, held, root):
        if not (isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return
        cm = self.m.class_of(fi)
        if cm is None or node.attr in cm.locks:
            return
        key = (cm.key, node.attr)
        self.access_roots.setdefault(key, set()).add(root)
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            tail = fi.qualname.rsplit(".", 1)[-1]
            if tail in ("__init__", "__enter__"):
                return
            self.writes.setdefault(key, []).append(
                (fi, node, not held, root))


def _analysis(project: ProjectIndex) -> "_Analysis":
    """The walk is shared by all three rules; cache it per project."""
    cached = getattr(project, "_lock_analysis", None)
    if cached is None:
        cached = _Analysis(_Model(project))
        project._lock_analysis = cached
    return cached


def _find_cycles(edges: dict) -> list[list]:
    graph: dict = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    cycles, seen_cycles = [], set()

    def dfs(start, node, path, visited):
        for nxt in graph.get(node, ()):
            if nxt == start:
                canon = frozenset(path)
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    cycles.append(path[:])
            elif nxt not in visited and len(path) < 8:
                visited.add(nxt)
                dfs(start, nxt, path + [nxt], visited)
                visited.discard(nxt)

    for start in graph:
        dfs(start, start, [start], {start})
    return cycles


def _fmt(lid) -> str:
    return f"{lid[0].split('.')[-1]}.{lid[1]}"


class LockOrderCycle:
    """A cycle in the static lock-acquisition graph, or re-acquiring a
    held non-reentrant Lock — either one can deadlock at runtime."""

    name = "lock-order-cycle"

    def run(self, project: ProjectIndex):
        ana = _analysis(project)
        for cycle in _find_cycles(ana.edges):
            pair = (cycle + cycle[:1])[:2]
            fi, node = ana.edges.get(
                (pair[0], pair[1]), next(iter(ana.edges.values())))
            order = " -> ".join(_fmt(c) for c in cycle + cycle[:1])
            yield project.finding(
                self.name, fi, node,
                f"lock acquisition cycle {order}: threads taking these "
                f"locks in different orders can deadlock")
        for lid, fi, node in ana.reacquire:
            yield project.finding(
                self.name, fi, node,
                f"re-acquiring non-reentrant lock {_fmt(lid)} while "
                f"already held: self-deadlock")


class UnlockedSharedWrite:
    """A ``self.attr`` write outside ``__init__`` with no lock held on
    some path, for an attribute reachable from two or more distinct
    thread roots — a data race unless a documented protocol protects
    it (then: baseline with a justification)."""

    name = "unlocked-shared-write"

    def run(self, project: ProjectIndex):
        ana = _analysis(project)
        emitted = set()
        for key, records in sorted(ana.writes.items()):
            roots = ana.access_roots.get(key, set())
            if len(roots) < 2:
                continue
            for fi, node, unlocked, root in records:
                if not unlocked:
                    continue
                loc = (key, node.lineno, node.col_offset)
                if loc in emitted:
                    continue
                emitted.add(loc)
                others = sorted(r for r in roots if r != root)[:3]
                yield project.finding(
                    self.name, fi, node,
                    f"unlocked write to shared `self.{key[1]}` (also "
                    f"reached from {', '.join(others)})")


class LockedSuffixCall:
    """A ``*_locked``-named method called with no lock held — the
    naming contract says the caller must already own the lock."""

    name = "locked-suffix-call"

    def run(self, project: ProjectIndex):
        ana = _analysis(project)
        emitted = set()
        for d, fi, node in ana.unlocked_calls:
            loc = (fi.module.fctx.relpath, node.lineno, d)
            if loc in emitted:
                continue
            emitted.add(loc)
            yield project.finding(
                self.name, fi, node,
                f"`{d}()` called without holding any lock; the _locked "
                f"suffix requires the caller to own it")


RULES = (LockOrderCycle, UnlockedSharedWrite, LockedSuffixCall)
