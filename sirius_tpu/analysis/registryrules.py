"""Registry-consistency rules.

Five registries keep names honest across subsystem boundaries:
``config/schema.py``'s ``ControlConfig`` fields (every ``control.*``
read), ``utils/faults.py``'s ``KNOWN_SITES`` (every fault-injection
site literal), ``obs/costs.py``'s ``scf_stage_costs`` keys plus
``UNCOSTED_SPANS`` (every ``scf.*``/``md.*``/``serve.*``/``campaign.*``
span name), ``obs/events.py``'s ``KNOWN_EVENT_KINDS`` (every
``emit(kind, ...)`` literal), and ``obs/metrics.py``'s
``KNOWN_METRIC_NAMES`` (every ``REGISTRY.counter/gauge/histogram``
name literal in production code — tests register throwaway names on
private registries and are exempt).
Each registry is parsed *by AST* from the live source — never imported
— so the lint works in any environment and the registries cannot drift
from what the rule checks.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

from sirius_tpu.analysis.core import (
    ProjectIndex,
    call_name,
    dotted_name,
)

_SPAN_RE = re.compile(
    r"^(scf|md|serve|campaign|trace|collective)\.[a-z_][a-z0-9_.]*$")


@dataclasses.dataclass
class RegistryConfig:
    """Override any field in tests; ``None`` disables that family."""

    control_keys: frozenset | None = None
    fault_sites: frozenset | None = None
    span_keys: frozenset | None = None
    event_kinds: frozenset | None = None
    metric_names: frozenset | None = None


def _module_tree(project: ProjectIndex, suffix: str,
                 relsrc: str) -> ast.AST | None:
    for mi in project.modules.values():
        if mi.name.endswith(suffix):
            return mi.fctx.tree
    path = os.path.join(project.root, relsrc)
    if os.path.exists(path):
        try:
            with open(path, encoding="utf-8") as fh:
                return ast.parse(fh.read(), filename=path)
        except (OSError, SyntaxError):
            return None
    return None


def _control_keys(tree: ast.AST) -> frozenset | None:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "ControlConfig":
            keys = set()
            for sub in node.body:
                if isinstance(sub, ast.AnnAssign) and isinstance(
                        sub.target, ast.Name):
                    keys.add(sub.target.id)
            return frozenset(keys)
    return None


def _tuple_of_strings(tree: ast.AST, name: str) -> frozenset | None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == name
                   for t in node.targets):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List, ast.Set)):
            out = {e.value for e in node.value.elts
                   if isinstance(e, ast.Constant)
                   and isinstance(e.value, str)}
            return frozenset(out)
    return None


def _span_keys(tree: ast.AST) -> frozenset | None:
    keys: set[str] = set()
    found = False
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == (
                "scf_stage_costs"):
            found = True
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Subscript)
                        and isinstance(sub.slice, ast.Constant)
                        and isinstance(sub.slice.value, str)):
                    keys.add(sub.slice.value)
    uncosted = _tuple_of_strings(tree, "UNCOSTED_SPANS")
    if uncosted:
        found = True
        keys |= uncosted
    return frozenset(keys) if found else None


def load_registry(project: ProjectIndex) -> RegistryConfig:
    schema = _module_tree(project, "config.schema",
                          "sirius_tpu/config/schema.py")
    faults = _module_tree(project, "utils.faults",
                          "sirius_tpu/utils/faults.py")
    costs = _module_tree(project, "obs.costs", "sirius_tpu/obs/costs.py")
    events = _module_tree(project, "obs.events", "sirius_tpu/obs/events.py")
    metrics = _module_tree(project, "obs.metrics",
                           "sirius_tpu/obs/metrics.py")
    return RegistryConfig(
        control_keys=_control_keys(schema) if schema else None,
        fault_sites=(_tuple_of_strings(faults, "KNOWN_SITES")
                     if faults else None),
        span_keys=_span_keys(costs) if costs else None,
        event_kinds=(_tuple_of_strings(events, "KNOWN_EVENT_KINDS")
                     if events else None),
        metric_names=(_tuple_of_strings(metrics, "KNOWN_METRIC_NAMES")
                      if metrics else None),
    )


_CONTROL_BASES = {"control", "ctl", "ctrl"}
_NOT_FIELDS = {"get", "items", "keys", "values", "replace", "copy",
               "asdict"}


class UnknownControlKey:
    """A ``*.control.<key>`` read for a key that is not a
    ``ControlConfig`` field — it would raise AttributeError at runtime
    (or, via getattr default, silently never fire)."""

    name = "unknown-control-key"
    wants_registry = True

    def run(self, project: ProjectIndex, registry=None):
        reg = registry or load_registry(project)
        keys = reg.control_keys
        if keys is None:
            return
        for mi in project.modules.values():
            if mi.name.endswith("config.schema"):
                continue
            fctx = mi.fctx
            for node in ast.walk(fctx.tree):
                key = None
                if isinstance(node, ast.Attribute):
                    base = node.value
                    if (isinstance(base, ast.Attribute)
                            and base.attr == "control"):
                        key = node.attr
                    elif (isinstance(base, ast.Name)
                          and base.id in _CONTROL_BASES):
                        key = node.attr
                elif isinstance(node, ast.Call) and call_name(
                        node) == "getattr" and len(node.args) >= 2:
                    tgt = node.args[0]
                    d = dotted_name(tgt)
                    if d and (d.endswith(".control")
                              or d in _CONTROL_BASES):
                        a = node.args[1]
                        if isinstance(a, ast.Constant) and isinstance(
                                a.value, str):
                            key = a.value
                if (key is None or key in keys or key.startswith("_")
                        or key in _NOT_FIELDS):
                    continue
                yield project.finding(
                    self.name, fctx, node,
                    f"`control.{key}` is not a ControlConfig field in "
                    f"config/schema.py")


class UnknownFaultSite:
    """A fault-injection call naming a site that is not in
    ``utils/faults.KNOWN_SITES`` — the spec grammar would accept it and
    the fault would silently never fire."""

    name = "unknown-fault-site"
    wants_registry = True
    _FNS = {"armed", "check", "corrupt", "fire"}

    def run(self, project: ProjectIndex, registry=None):
        reg = registry or load_registry(project)
        sites = reg.fault_sites
        if sites is None:
            return
        for fctx in project.files:
            for node in ast.walk(fctx.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in self._FNS):
                    continue
                base = dotted_name(node.func.value)
                if not base or not base.split(".")[-1] == "faults":
                    continue
                if not node.args:
                    continue
                a = node.args[0]
                if not (isinstance(a, ast.Constant)
                        and isinstance(a.value, str)):
                    continue
                if a.value in sites:
                    continue
                yield project.finding(
                    self.name, fctx, node,
                    f"fault site \"{a.value}\" is not in "
                    f"utils/faults.KNOWN_SITES")


class UncostedSpan:
    """A span name wired into the observability layer with neither a
    ``scf_stage_costs()`` flop model nor an ``UNCOSTED_SPANS``
    exemption — the attribution report would show it with 0 FLOPs and
    skew MFU percentages."""

    name = "uncosted-span"
    wants_registry = True
    _FNS = {"record", "span", "_stage_record"}

    def run(self, project: ProjectIndex, registry=None):
        reg = registry or load_registry(project)
        spans = reg.span_keys
        if spans is None:
            return
        for fctx in project.files:
            if fctx.relpath.endswith(("obs/costs.py", "utils/faults.py")):
                continue
            for node in ast.walk(fctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                d = call_name(node)
                if not d or d.split(".")[-1] not in self._FNS:
                    continue
                if not node.args:
                    continue
                a = node.args[0]
                if not (isinstance(a, ast.Constant)
                        and isinstance(a.value, str)
                        and _SPAN_RE.match(a.value)):
                    continue
                if a.value in spans:
                    continue
                yield project.finding(
                    self.name, fctx, node,
                    f"span \"{a.value}\" has no scf_stage_costs() key "
                    f"and no UNCOSTED_SPANS exemption in obs/costs.py")


def _literal_strings(node: ast.AST) -> list[str]:
    """String literal(s) an argument expression evaluates to: plain
    constants plus both arms of a conditional expression
    (``emit("drain" if mode == "drain" else "abort", ...)``)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, ast.IfExp):
        return _literal_strings(node.body) + _literal_strings(node.orelse)
    return []


class UnknownEventKind:
    """An ``obs.events.emit(kind, ...)`` literal not registered in
    ``obs/events.KNOWN_EVENT_KINDS`` — the event would be written but
    no consumer (trace exporter, replayer, dashboards) knows the kind
    exists, so it silently vanishes from every downstream view."""

    name = "unknown-event-kind"
    wants_registry = True
    _BASES = {"events", "obs", "obs_events", "_events"}

    def run(self, project: ProjectIndex, registry=None):
        reg = registry or load_registry(project)
        kinds = reg.event_kinds
        if kinds is None:
            return
        for fctx in project.files:
            if (fctx.relpath.startswith("tests/")
                    or fctx.relpath.endswith("obs/events.py")):
                continue
            for node in ast.walk(fctx.tree):
                if not (isinstance(node, ast.Call) and node.args):
                    continue
                if isinstance(node.func, ast.Name):
                    if node.func.id != "emit":
                        continue
                elif isinstance(node.func, ast.Attribute):
                    if node.func.attr != "emit":
                        continue
                    base = dotted_name(node.func.value)
                    if not base or base.split(".")[-1] not in self._BASES:
                        continue
                else:
                    continue
                for kind in _literal_strings(node.args[0]):
                    if kind in kinds:
                        continue
                    yield project.finding(
                        self.name, fctx, node,
                        f"event kind \"{kind}\" is not in "
                        f"obs/events.KNOWN_EVENT_KINDS")


class UnknownMetricName:
    """A ``REGISTRY.counter/gauge/histogram(name, ...)`` literal not
    registered in ``obs/metrics.KNOWN_METRIC_NAMES`` — the series would
    be exported under a name no dashboard query or CI smoke assertion
    knows about. Private per-test registries (any base other than the
    module-level ``REGISTRY``) are exempt."""

    name = "unknown-metric-name"
    wants_registry = True
    _KINDS = {"counter", "gauge", "histogram"}

    def run(self, project: ProjectIndex, registry=None):
        reg = registry or load_registry(project)
        names = reg.metric_names
        if names is None:
            return
        for fctx in project.files:
            if fctx.relpath.startswith("tests/"):
                continue
            for node in ast.walk(fctx.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in self._KINDS
                        and node.args):
                    continue
                base = dotted_name(node.func.value)
                if not base or base.split(".")[-1] != "REGISTRY":
                    continue
                for mname in _literal_strings(node.args[0]):
                    if mname in names:
                        continue
                    yield project.finding(
                        self.name, fctx, node,
                        f"metric \"{mname}\" is not in "
                        f"obs/metrics.KNOWN_METRIC_NAMES")


RULES = (UnknownControlKey, UnknownFaultSite, UncostedSpan,
         UnknownEventKind, UnknownMetricName)
