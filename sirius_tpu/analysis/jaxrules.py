"""JAX rules: invariants of jit-reachable (traced) code.

Every rule here is scoped to the jit-reachability closure computed by
:meth:`ProjectIndex.jit_reachable` — host-path code is free to use
numpy, Python control flow, and ``float()`` readbacks, so flagging it
would drown the signal. Taint is intra-function and deliberately
shallow: a value is "tracer-ish" iff it flows (through assignments and
expressions) from a ``jnp.*`` / ``jax.lax.*`` call, which keeps
Python-bool conditionals like ``if polarized:`` inside device code
clean while still catching ``if jnp.max(r) > tol:``.
"""

from __future__ import annotations

import ast

from sirius_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    ProjectIndex,
    assigned_names,
    call_name,
    dotted_name,
)

_ARRAY_PREFIXES = ("jnp.", "jax.numpy.", "jax.lax.", "lax.")
_NUMPY_PREFIXES = ("np.", "numpy.", "scipy.", "sp.")
_DTYPELESS_CTORS = {"zeros", "ones", "empty", "full", "arange",
                    "linspace", "eye", "zeros_like_none"}


def _is_array_call(d: str) -> bool:
    return d.startswith(_ARRAY_PREFIXES)


def tainted_names(fn_node: ast.AST) -> set[str]:
    """Names that (transitively) hold results of jnp/lax calls."""
    tainted: set[str] = set()

    def expr_tainted(e: ast.AST) -> bool:
        for n in ast.walk(e):
            if isinstance(n, ast.Call):
                d = call_name(n)
                if d and _is_array_call(d):
                    return True
            elif (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
                  and n.id in tainted):
                return True
        return False

    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn_node):
            targets: list[ast.AST] = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets, value = [node.target], node.value
            if value is None or not expr_tainted(value):
                continue
            for t in targets:
                for nm in assigned_names(t):
                    if nm not in tainted:
                        tainted.add(nm)
                        changed = True
    return tainted


def _expr_is_tainted(e: ast.AST, tainted: set[str]) -> bool:
    for n in ast.walk(e):
        if isinstance(n, ast.Call):
            d = call_name(n)
            if d and _is_array_call(d):
                return True
        elif (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
              and n.id in tainted):
            return True
    return False


def _jit_functions(project: ProjectIndex):
    reach = project.jit_reachable()
    for fi in project.iter_functions():
        if fi.key in reach:
            yield fi


class JitTracedControlFlow:
    """Python ``if``/``while`` branching on a traced array value —
    resolved at trace time, so it either crashes (ConcretizationError)
    or silently bakes in one branch and recompiles per shape."""

    name = "jit-traced-control-flow"

    def run(self, project: ProjectIndex):
        for fi in _jit_functions(project):
            tainted = tainted_names(fi.node)
            for node in ast.walk(fi.node):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                test = node.test
                if (isinstance(test, ast.Compare) and all(
                        isinstance(op, (ast.Is, ast.IsNot))
                        for op in test.ops)):
                    continue  # `x is None`: identity, static at trace time
                if _expr_is_tainted(test, tainted):
                    kw = "while" if isinstance(node, ast.While) else "if"
                    yield project.finding(
                        self.name, fi, node,
                        f"Python `{kw}` on a traced array value in "
                        f"jit-reachable `{fi.qualname}`; use jnp.where / "
                        f"lax.cond / lax.while_loop")


class JitNumpyCall:
    """``np.*``/``scipy.*`` calls inside jit-reachable code run on host
    at trace time — a silent device→host sync plus a constant baked
    into the executable."""

    name = "jit-numpy-call"

    def run(self, project: ProjectIndex):
        for fi in _jit_functions(project):
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                d = call_name(node)
                if d and d.startswith(_NUMPY_PREFIXES):
                    yield project.finding(
                        self.name, fi, node,
                        f"host numpy call `{d}` in jit-reachable "
                        f"`{fi.qualname}`; use the jnp equivalent")


class JitHostSync:
    """Implicit device→host syncs (``float()``/``.item()``/
    ``np.asarray()`` on traced values) — each one stalls the dispatch
    pipeline. Sanctioned readback sites carry an inline suppression."""

    name = "jit-host-sync"
    _CASTS = {"float", "int", "bool", "complex"}
    _SYNC_METHODS = {"item", "tolist", "block_until_ready"}

    def _in_scope(self, project, fi: FunctionInfo, reach) -> bool:
        if fi.key in reach:
            return True
        tail = fi.qualname.rsplit(".", 1)[-1]
        return tail.endswith("_device") or tail.startswith("device_")

    def run(self, project: ProjectIndex):
        reach = project.jit_reachable()
        for fi in project.iter_functions():
            if not self._in_scope(project, fi, reach):
                continue
            tainted = tainted_names(fi.node)
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                d = call_name(node)
                if d in self._CASTS and node.args and _expr_is_tainted(
                        node.args[0], tainted):
                    yield project.finding(
                        self.name, fi, node,
                        f"`{d}()` on a traced value in `{fi.qualname}` "
                        f"forces a device->host sync")
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr in self._SYNC_METHODS):
                    yield project.finding(
                        self.name, fi, node,
                        f"`.{node.func.attr}()` in jit-scope "
                        f"`{fi.qualname}` forces a device->host sync")
                elif (d in ("np.asarray", "np.array", "numpy.asarray",
                            "numpy.array") and node.args
                      and _expr_is_tainted(node.args[0], tainted)):
                    yield project.finding(
                        self.name, fi, node,
                        f"`{d}()` on a traced value in `{fi.qualname}` "
                        f"copies the buffer to host")


def _int_elements(node: ast.AST) -> list[int]:
    """Literal ints from an int or tuple-of-ints AST node."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    out = []
    if isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
    return out


def _str_elements(node: ast.AST) -> list[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    out = []
    if isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
    return out


def _local_jit_bindings(fn_node: ast.AST):
    """``name = jax.jit(f, ...)`` / ``self.attr = jax.jit(f, ...)``
    bindings inside one function: yields (binding, kwargs, assign)."""
    from sirius_tpu.analysis.core import _JIT_WRAPPERS

    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        v = node.value
        if not (isinstance(v, ast.Call) and call_name(v) in _JIT_WRAPPERS):
            continue
        tgt = dotted_name(node.targets[0])
        if tgt:
            yield tgt, {k.arg: k.value for k in v.keywords if k.arg}, node


class JitDonatedReuse:
    """Reading an argument after passing it at a ``donate_argnums``
    position — the buffer has been handed to XLA and may alias the
    output; reuse is undefined behaviour."""

    name = "jit-donated-reuse"

    def _donated_map(self, project):
        """(module, owner-name) -> donated positions, from both local
        ``g = jax.jit(f, donate_argnums=...)`` bindings and
        ``self.X = jax.jit(...)`` class-level bindings."""
        out: dict[tuple[str, str, str], list[int]] = {}
        for fi in project.iter_functions():
            for tgt, kwargs, _ in _local_jit_bindings(fi.node):
                if "donate_argnums" not in kwargs:
                    continue
                pos = _int_elements(kwargs["donate_argnums"])
                if not pos:
                    continue
                if tgt.startswith("self.") and fi.cls:
                    out[(fi.module.name, fi.cls, tgt)] = pos
                else:
                    # local binding: scoped to this function only
                    out[(fi.module.name, fi.qualname, tgt)] = pos
        return out

    def run(self, project: ProjectIndex):
        donated = self._donated_map(project)
        if not donated:
            return
        for fi in project.iter_functions():
            scopes = [(fi.module.name, fi.qualname),
                      (fi.module.name, fi.cls or "")]
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                d = call_name(node)
                if not d:
                    continue
                pos = None
                for sm, so in scopes:
                    pos = donated.get((sm, so, d))
                    if pos:
                        break
                if not pos:
                    continue
                donated_args = {
                    a.id for i, a in enumerate(node.args)
                    if i in pos and isinstance(a, ast.Name)}
                if not donated_args:
                    continue
                for later in ast.walk(fi.node):
                    if (isinstance(later, ast.Name)
                            and isinstance(later.ctx, ast.Load)
                            and later.id in donated_args
                            and later.lineno > node.lineno):
                        yield project.finding(
                            self.name, fi, later,
                            f"`{later.id}` read after being donated to "
                            f"`{d}` (line {node.lineno}); the buffer may "
                            f"alias the output")
                        donated_args.discard(later.id)
                        if not donated_args:
                            break


class JitDtypeLiteral:
    """Array constructors without an explicit ``dtype=`` in
    jit-reachable code default to the ambient x64 setting — a silent
    precision fork once the mixed-precision ladder lands."""

    name = "jit-dtype-literal"

    def run(self, project: ProjectIndex):
        for fi in _jit_functions(project):
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                d = call_name(node)
                if not d or not d.startswith(("jnp.", "jax.numpy.")):
                    continue
                ctor = d.rsplit(".", 1)[-1]
                if ctor not in {"zeros", "ones", "empty", "full",
                                "arange", "linspace", "eye"}:
                    continue
                if any(k.arg == "dtype" for k in node.keywords):
                    continue
                # positional dtype: zeros(shape, dtype) / full(sh, v, dtype)
                min_args = {"zeros": 1, "ones": 1, "empty": 1, "full": 2}
                if ctor in min_args and len(node.args) > min_args[ctor]:
                    continue
                yield project.finding(
                    self.name, fi, node,
                    f"`{d}(...)` without dtype= in jit-reachable "
                    f"`{fi.qualname}`; pin the precision explicitly")


class JitPythonFloatAccum:
    """A Python scalar initialised from a literal and then accumulated
    with traced values — every trace re-materialises it as a fresh
    constant, defeating donation and promoting dtype weakly."""

    name = "jit-python-float-accum"

    def run(self, project: ProjectIndex):
        for fi in _jit_functions(project):
            tainted = tainted_names(fi.node)
            literal_inits: set[str] = set()
            for node in ast.walk(fi.node):
                if (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, (int, float))):
                    literal_inits.update(
                        nm for t in node.targets for nm in
                        assigned_names(t))
            if not literal_inits:
                continue
            for node in ast.walk(fi.node):
                if (isinstance(node, ast.AugAssign)
                        and isinstance(node.target, ast.Name)
                        and node.target.id in literal_inits
                        and _expr_is_tainted(node.value, tainted)):
                    yield project.finding(
                        self.name, fi, node,
                        f"Python scalar `{node.target.id}` accumulated "
                        f"with traced values in `{fi.qualname}`; "
                        f"initialise it as a jnp array")


class JitNonHashableStatic:
    """A list/dict/set passed at a ``static_argnums`` position — jit
    hashes static args for the compile cache, so this raises (or worse,
    with custom __hash__, caches wrongly)."""

    name = "jit-nonhashable-static"
    _BAD = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
            ast.SetComp)

    def _static_info(self, fi: FunctionInfo):
        pos = _int_elements(fi.jit_kwargs.get("static_argnums",
                                              ast.Constant(value=None)))
        names = _str_elements(fi.jit_kwargs.get("static_argnames",
                                                ast.Constant(value=None)))
        return pos, names

    def run(self, project: ProjectIndex):
        project.jit_reachable()  # populates jit_kwargs on seeds
        static: dict[tuple[str, str], tuple[list[int], list[str]]] = {}
        for fi in project.iter_functions():
            if fi.jit_kwargs:
                p, n = self._static_info(fi)
                if p or n:
                    static[fi.key] = (p, n)
        # local bindings: g = jax.jit(f, static_argnums=(1,)) then g([..])
        for fi in project.iter_functions():
            local: dict[str, tuple[list[int], list[str]]] = {}
            for tgt, kwargs, _ in _local_jit_bindings(fi.node):
                p = _int_elements(kwargs.get("static_argnums",
                                             ast.Constant(value=None)))
                n = _str_elements(kwargs.get("static_argnames",
                                             ast.Constant(value=None)))
                if p or n:
                    local[tgt] = (p, n)
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                d = call_name(node)
                if not d:
                    continue
                info = local.get(d)
                if info is None:
                    for tgt in project._resolve_call(fi.module, fi.cls, d):
                        info = static.get(tgt.key)
                        if info:
                            break
                if not info:
                    continue
                pos, names = info
                for i, a in enumerate(node.args):
                    if i in pos and isinstance(a, self._BAD):
                        yield project.finding(
                            self.name, fi, a,
                            f"non-hashable literal at static position "
                            f"{i} of `{d}`; use a tuple")
                for k in node.keywords:
                    if k.arg in names and isinstance(k.value, self._BAD):
                        yield project.finding(
                            self.name, fi, k.value,
                            f"non-hashable literal for static arg "
                            f"`{k.arg}` of `{d}`; use a tuple")


RULES = (
    JitTracedControlFlow,
    JitNumpyCall,
    JitHostSync,
    JitDonatedReuse,
    JitDtypeLiteral,
    JitPythonFloatAccum,
    JitNonHashableStatic,
)
