"""SARIF 2.1.0 output for sirius-lint.

SARIF (Static Analysis Results Interchange Format) is what code-review
UIs ingest to annotate diffs inline: one ``run`` with a ``tool.driver``
rule catalog and one ``result`` per finding. We emit the minimal valid
document — rule metadata from each rule class's docstring, physical
locations with 1-based line/column, and the rename-stable fingerprint
under ``partialFingerprints`` so viewers can track a finding across
commits the same way LINT_BASELINE.json does.

Only the stdlib is used; the document is plain dicts serialised by the
caller (``sirius-lint --sarif PATH``).
"""

from __future__ import annotations

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")
FINGERPRINT_KEY = "siriusLint/v2"


def _rule_descriptor(rule_cls) -> dict:
    doc = " ".join((rule_cls.__doc__ or "").split())
    short = doc.split(". ")[0].rstrip(".") if doc else rule_cls.name
    return {
        "id": rule_cls.name,
        "shortDescription": {"text": short[:240] or rule_cls.name},
        "fullDescription": {"text": doc or rule_cls.name},
        "defaultConfiguration": {"level": "warning"},
    }


def _result(finding, baselined: bool) -> dict:
    return {
        "ruleId": finding.rule,
        "level": "note" if baselined else "warning",
        "message": {"text": finding.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": finding.path,
                                     "uriBaseId": "SRCROOT"},
                "region": {"startLine": max(finding.line, 1),
                           "startColumn": max(finding.col + 1, 1)},
            },
        }],
        "partialFingerprints": {FINGERPRINT_KEY: finding.fingerprint},
        # SARIF baselineState is exactly our baseline semantics:
        # "unchanged" findings are accepted debt, "new" ones fail CI
        "baselineState": "unchanged" if baselined else "new",
    }


def to_sarif(findings, rules, new=None, root: str = ".") -> dict:
    """Build the SARIF document. ``findings`` is the full list,
    ``new`` the subset that is new vs the baseline (``None`` means no
    baseline: everything is new)."""
    new_keys = None
    if new is not None:
        new_keys = {(f.rule, f.path, f.line, f.col, f.message)
                    for f in new}
    results = []
    for f in findings:
        key = (f.rule, f.path, f.line, f.col, f.message)
        baselined = new_keys is not None and key not in new_keys
        results.append(_result(f, baselined))
    return {
        "version": SARIF_VERSION,
        "$schema": SARIF_SCHEMA,
        "runs": [{
            "tool": {"driver": {
                "name": "sirius-lint",
                "informationUri":
                    "https://example.invalid/sirius_tpu/analysis",
                "rules": [_rule_descriptor(r) for r in rules],
            }},
            "originalUriBaseIds": {"SRCROOT": {"uri": f"file://{root}/"}},
            "results": results,
            "columnKind": "utf16CodeUnits",
        }],
    }
