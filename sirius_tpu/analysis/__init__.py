"""sirius-lint: JAX-aware static analysis for the sirius_tpu tree.

Three rule families keep the invariants the test suite cannot check
mechanically:

- **JAX rules** (analysis/jaxrules.py), scoped to *jit-reachable*
  functions (the transitive closure of every ``jax.jit``/``jax.pmap``
  seed and ``jax.lax`` higher-order body over the project call graph):
  tracer-hostile Python control flow, ``np.*`` calls and Python-float
  accumulation inside compiled code, implicit host syncs, donated-buffer
  reuse, dtype-less array creation (the fp64-path drift groundwork for
  the mixed-precision ladder), and non-hashable static arguments.
- **Concurrency rules** (analysis/lockrules.py) for the threaded
  ``serve/`` modules: a static lock-acquisition graph built from
  ``with self._lock:`` nesting and called-method edges (Condition
  aliasing resolved), cycle detection (potential deadlock), unlocked
  shared-attribute writes reachable from two threads, and the
  ``*_locked``-naming contract.
- **Registry-consistency rules** (analysis/registryrules.py): every
  ``control.*`` read must name a ``config/schema.py`` field, every
  fault-site literal must be in ``utils/faults.KNOWN_SITES``, and every
  ``scf.*``/``md.*`` span must have an ``obs/costs.scf_stage_costs``
  key or an ``UNCOSTED_SPANS`` exemption.

Findings are suppressed per line with ``# sirius-lint: disable=RULE``
(or ``disable=*``), per file with ``# sirius-lint: disable-file=RULE``,
and per tree with the checked-in ``LINT_BASELINE.json`` — CI fails only
on *new* violations (``sirius-lint --baseline LINT_BASELINE.json``).
"""

from sirius_tpu.analysis.core import (  # noqa: F401
    Finding,
    LintEngine,
    ProjectIndex,
    all_rules,
    load_baseline,
    write_baseline,
)
