"""sirius-lint: JAX-aware static analysis for the sirius_tpu tree.

Six rule families keep the invariants the test suite cannot check
mechanically:

- **JAX rules** (analysis/jaxrules.py), scoped to *jit-reachable*
  functions (the transitive closure of every ``jax.jit``/``jax.pmap``
  seed and ``jax.lax`` higher-order body over the project call graph):
  tracer-hostile Python control flow, ``np.*`` calls and Python-float
  accumulation inside compiled code, implicit host syncs, donated-buffer
  reuse, dtype-less array creation (the fp64-path drift groundwork for
  the mixed-precision ladder), and non-hashable static arguments.
- **Concurrency rules** (analysis/lockrules.py) for the threaded
  ``serve/`` modules: a static lock-acquisition graph built from
  ``with self._lock:`` nesting and called-method edges (Condition
  aliasing resolved), cycle detection (potential deadlock), unlocked
  shared-attribute writes reachable from two threads, and the
  ``*_locked``-naming contract.
- **Registry-consistency rules** (analysis/registryrules.py): every
  ``control.*`` read must name a ``config/schema.py`` field, every
  fault-site literal must be in ``utils/faults.KNOWN_SITES``, every
  ``scf.*``/``md.*`` span must have an ``obs/costs.scf_stage_costs``
  key or an ``UNCOSTED_SPANS`` exemption, every ``emit(kind, ...)``
  literal must be in ``obs/events.KNOWN_EVENT_KINDS``, and every
  production ``REGISTRY.counter/gauge/histogram`` name must be in
  ``obs/metrics.KNOWN_METRIC_NAMES``.
- **Recompile-hazard rules** (analysis/compilerules.py), built on the
  interprocedural device-dataflow model in analysis/dataflow.py:
  ``jax.jit`` wrappers constructed inside loop bodies, per-call-varying
  values (loop indices, ``time.*``/``random.*``) at
  ``static_argnums``/``static_argnames`` positions, and the
  serve/cache.py cross-check — any ``self.<attr>`` a cache-shared
  jitted impl reads but its ``_trace_signature()`` omits.
- **Transfer-budget rules** (analysis/transferrules.py): device→host
  crossings statically enumerated from the dataflow model and checked
  against the checked-in ``TRANSFER_BUDGET.json`` manifest — the fused
  SCF loop's one-readback-per-iteration contract is *proved* at the
  AST level, attributable to source lines.
- **Sharding-consistency rules** (analysis/shardrules.py): a static
  mesh/axis model (every ``Mesh(...)`` construction and producer),
  collective ``axis_name``s checked against declared axes,
  NamedSharding/shard_map spec-vs-mesh mismatches,
  ``with_sharding_constraint`` in jit-reachable loop bodies, and the
  per-driver sharding inventory (``sirius-lint --report sharding``).

Findings are suppressed per line with ``# sirius-lint: disable=RULE``
(or ``disable=*``), per file with ``# sirius-lint: disable-file=RULE``,
and per tree with the checked-in ``LINT_BASELINE.json`` — CI fails only
on *new* violations (``sirius-lint --baseline LINT_BASELINE.json``).
Baseline fingerprints are rename-stable: keyed on (rule, normalized
finding text, enclosing qualname), never on path or line. Stale
suppressions are audited by ``sirius-lint --check-suppressions``
(``--strict`` fails on them) and SARIF 2.1.0 output for review UIs
comes from ``--sarif PATH``.
"""

from sirius_tpu.analysis.core import (  # noqa: F401
    Finding,
    LintEngine,
    ProjectIndex,
    all_rules,
    load_baseline,
    write_baseline,
)
