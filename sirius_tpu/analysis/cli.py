"""``sirius-lint`` CLI.

Typical use::

    sirius-lint                                  # lint the whole tree
    sirius-lint sirius_tpu/serve                 # one subtree
    sirius-lint --baseline LINT_BASELINE.json    # CI mode: new findings only
    sirius-lint --write-baseline LINT_BASELINE.json   # accept current state
    sirius-lint --list-rules                     # rule catalog

Exit codes: 0 = clean (or nothing new vs the baseline), 1 = findings,
2 = unparseable inputs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from sirius_tpu.analysis.core import (
    DEFAULT_SCAN,
    LintEngine,
    all_rules,
    collect_files,
    load_baseline,
    new_findings,
    write_baseline,
)


def _detect_root(root: str | None) -> str:
    if root:
        return os.path.abspath(root)
    cwd = os.getcwd()
    if os.path.isdir(os.path.join(cwd, "sirius_tpu")):
        return cwd
    import sirius_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(
        sirius_tpu.__file__)))


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="sirius-lint",
        description="JAX-aware static analysis for the sirius_tpu tree "
                    "(jit purity, serve lock discipline, registry "
                    "consistency)")
    p.add_argument("paths", nargs="*",
                   help=f"files/directories to lint (default: "
                        f"{' '.join(DEFAULT_SCAN)} under --root)")
    p.add_argument("--root", default=None,
                   help="repository root (default: auto-detected)")
    p.add_argument("--baseline", default=None,
                   help="compare against this baseline; only NEW findings "
                        "fail the run")
    p.add_argument("--write-baseline", default=None, metavar="PATH",
                   help="accept the current findings as the baseline "
                        "(preserves justifications for kept entries)")
    p.add_argument("--report", default=None, metavar="PATH",
                   help="write the full findings report as JSON (CI "
                        "artifact)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule-name filter")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    args = p.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            doc = " ".join((r.__doc__ or "").split())
            print(f"{r.name:24s} {doc}")
        return 0
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = wanted - {r.name for r in rules}
        if unknown:
            print(f"sirius-lint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.name in wanted]

    root = _detect_root(args.root)
    if args.paths:
        paths = collect_files(root, tuple(args.paths))
    else:
        targets = tuple(t for t in DEFAULT_SCAN
                        if os.path.exists(os.path.join(root, t)))
        paths = collect_files(root, targets)
    if not paths:
        print("sirius-lint: no python files to lint", file=sys.stderr)
        return 2

    engine = LintEngine(root, paths=paths, rules=rules)
    findings = engine.run()
    for err in engine.project.errors:
        print(f"sirius-lint: parse error: {err}", file=sys.stderr)

    if args.write_baseline:
        old = load_baseline(args.write_baseline)
        agg = write_baseline(args.write_baseline, findings, old)
        print(f"sirius-lint: baseline written to {args.write_baseline} "
              f"({len(findings)} finding(s), {len(agg)} fingerprint(s))")
        return 0

    shown = findings
    baseline = {}
    if args.baseline:
        baseline = load_baseline(args.baseline)
        shown = new_findings(findings, baseline)

    if args.report:
        report = {
            "root": root,
            "files": len(paths),
            "rules": [r.name for r in rules],
            "findings": [f.to_dict() for f in findings],
            "new_findings": [f.to_dict() for f in shown],
            "baselined": len(findings) - len(shown),
            "suppressed_inline": engine.suppressed_count,
        }
        with open(args.report, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=1)
            fh.write("\n")

    for f in shown:
        print(f)
    label = "new " if args.baseline else ""
    summary = (f"sirius-lint: {len(shown)} {label}finding(s) in "
               f"{len(paths)} file(s)")
    if args.baseline:
        summary += f" ({len(findings) - len(shown)} baselined)"
    if engine.suppressed_count:
        summary += f" ({engine.suppressed_count} suppressed inline)"
    print(summary)
    if engine.project.errors:
        return 2
    return 1 if shown else 0


if __name__ == "__main__":
    raise SystemExit(main())
