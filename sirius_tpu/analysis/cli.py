"""``sirius-lint`` CLI.

Typical use::

    sirius-lint                                  # lint the whole tree
    sirius-lint sirius_tpu/serve                 # one subtree
    sirius-lint --baseline LINT_BASELINE.json    # CI mode: new findings only
    sirius-lint --write-baseline LINT_BASELINE.json   # accept current state
    sirius-lint --list-rules                     # rule catalog
    sirius-lint --sarif lint.sarif               # SARIF 2.1.0 for review UIs
    sirius-lint --check-suppressions --strict    # stale-suppression audit
    sirius-lint --report sharding                # mesh/axis inventory (stdout)

Exit codes: 0 = clean (or nothing new vs the baseline), 1 = findings
(or, with --strict, stale suppressions), 2 = unparseable inputs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from sirius_tpu.analysis.core import (
    DEFAULT_SCAN,
    LintEngine,
    all_rules,
    collect_files,
    load_baseline,
    new_findings,
    write_baseline,
)


def _detect_root(root: str | None) -> str:
    if root:
        return os.path.abspath(root)
    cwd = os.getcwd()
    if os.path.isdir(os.path.join(cwd, "sirius_tpu")):
        return cwd
    import sirius_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(
        sirius_tpu.__file__)))


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="sirius-lint",
        description="JAX-aware static analysis for the sirius_tpu tree "
                    "(jit purity, serve lock discipline, registry "
                    "consistency, recompile hazards, transfer budgets, "
                    "sharding consistency)")
    p.add_argument("paths", nargs="*",
                   help=f"files/directories to lint (default: "
                        f"{' '.join(DEFAULT_SCAN)} under --root)")
    p.add_argument("--root", default=None,
                   help="repository root (default: auto-detected)")
    p.add_argument("--baseline", default=None,
                   help="compare against this baseline; only NEW findings "
                        "fail the run")
    p.add_argument("--write-baseline", default=None, metavar="PATH",
                   help="accept the current findings as the baseline "
                        "(preserves justifications for kept entries)")
    p.add_argument("--report", default=None, metavar="PATH",
                   help="write the full findings report as JSON (CI "
                        "artifact); the literal value `sharding` prints "
                        "the per-driver mesh/axis inventory to stdout "
                        "instead")
    p.add_argument("--sarif", default=None, metavar="PATH",
                   help="write findings as SARIF 2.1.0 (code-review "
                        "annotation format)")
    p.add_argument("--check-suppressions", action="store_true",
                   help="audit `# sirius-lint: disable=` comments that "
                        "silenced nothing (fixed violations or typo'd "
                        "rule names)")
    p.add_argument("--strict", action="store_true",
                   help="with --check-suppressions: stale suppressions "
                        "fail the run")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule-name filter")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    args = p.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            doc = " ".join((r.__doc__ or "").split())
            print(f"{r.name:24s} {doc}")
        return 0
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = wanted - {r.name for r in rules}
        if unknown:
            print(f"sirius-lint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.name in wanted]

    root = _detect_root(args.root)
    if args.paths:
        paths = collect_files(root, tuple(args.paths))
    else:
        targets = tuple(t for t in DEFAULT_SCAN
                        if os.path.exists(os.path.join(root, t)))
        paths = collect_files(root, targets)
    if not paths:
        print("sirius-lint: no python files to lint", file=sys.stderr)
        return 2

    engine = LintEngine(root, paths=paths, rules=rules)

    if args.report == "sharding":
        from sirius_tpu.analysis.shardrules import sharding_inventory

        json.dump(sharding_inventory(engine.project), sys.stdout, indent=1)
        sys.stdout.write("\n")
        return 2 if engine.project.errors else 0

    findings = engine.run()
    for err in engine.project.errors:
        print(f"sirius-lint: parse error: {err}", file=sys.stderr)

    if args.write_baseline:
        old = load_baseline(args.write_baseline)
        agg = write_baseline(args.write_baseline, findings, old)
        print(f"sirius-lint: baseline written to {args.write_baseline} "
              f"({len(findings)} finding(s), {len(agg)} fingerprint(s))")
        return 0

    shown = findings
    baseline = {}
    if args.baseline:
        baseline = load_baseline(args.baseline)
        shown = new_findings(findings, baseline)

    stale = []
    if args.check_suppressions:
        if args.rules:
            # a partial rule set can't tell "never fired" from "rule not
            # run"; the audit is only meaningful against the full catalog
            print("sirius-lint: --check-suppressions requires the full "
                  "rule catalog; drop --rules", file=sys.stderr)
            return 2
        stale = engine.stale_suppressions()
        for s in stale:
            print(f"{s['path']}:{s['line']}: stale suppression "
                  f"[{s['rule']}] ({s['reason']}): {s['text']}")

    if args.sarif:
        from sirius_tpu.analysis.sarif import to_sarif

        doc = to_sarif(findings, rules,
                       new=shown if args.baseline else None, root=root)
        with open(args.sarif, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=1)
            fh.write("\n")

    if args.report:
        report = {
            "root": root,
            "files": len(paths),
            "rules": [r.name for r in rules],
            "findings": [f.to_dict() for f in findings],
            "new_findings": [f.to_dict() for f in shown],
            "baselined": len(findings) - len(shown),
            "suppressed_inline": engine.suppressed_count,
            "stale_suppressions": stale,
        }
        with open(args.report, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=1)
            fh.write("\n")

    for f in shown:
        print(f)
    label = "new " if args.baseline else ""
    summary = (f"sirius-lint: {len(shown)} {label}finding(s) in "
               f"{len(paths)} file(s)")
    if args.baseline:
        summary += f" ({len(findings) - len(shown)} baselined)"
    if engine.suppressed_count:
        summary += f" ({engine.suppressed_count} suppressed inline)"
    if args.check_suppressions:
        summary += f" ({len(stale)} stale suppression(s))"
    print(summary)
    if engine.project.errors:
        return 2
    if shown:
        return 1
    if args.strict and stale:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
