"""Transfer-budget rules: device→host crossings vs a declared manifest.

The fused SCF loop's contract — *exactly one* ``[NUM_SCALARS]`` scalar
readback per iteration, everything else stays on device — is what the
runtime ``jax.transfer_guard`` test enforces dynamically. These rules
prove the same contract statically, attributable to source lines, from
the dataflow model in dataflow.py: a checked-in manifest
(``TRANSFER_BUDGET.json`` at the repo root) declares *regions* of named
functions and the number of crossings each may contain.

Manifest schema::

    {"version": 1, "regions": [
       {"path": "sirius_tpu/dft/scf.py", "function": "run_scf",
        "kind": "with:scf::fused_step", "budget": 0,
        "allowed": ["faults.corrupt"],   # exempt, but must still occur
        "note": "why this budget is what it is"},
       ...]}

Region kinds: ``with:NAME`` (every ``with profile("NAME")``-style block
whose context call takes the string literal NAME), ``if:COND`` /
``loop-if:COND`` (every ``if`` statement outside / inside a loop whose
test matches COND), ``loops`` (every ``for``/``while`` body in the
function), ``body`` (the whole function). A bare-identifier COND
matches any test *mentioning* that name; a COND with non-identifier
characters (``loop-if:fused is not None``) must equal the unparsed
test exactly — use the exact form when several guards mention the same
name. If-regions cover only the guarded body: the ``else`` branch is
the *opposite* path (usually the unconstrained host fallback) and is
never charged to the guard's budget.
A crossing is attributed to the *innermost* declared region containing
its line; crossings outside every declared region are unconstrained
(host-path code is free to read back). ``allowed`` substrings exempt
matching crossings from the count — but each pattern must still match
at least one crossing, so the manifest cannot rot silently.

Rules: ``transfer-budget`` (a region exceeds its budget — one finding
per excess crossing), ``transfer-stale-region`` (a manifest entry that
matches no function/AST region), ``transfer-stale-allowance`` (an
``allowed`` pattern that exempts nothing).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os

from sirius_tpu.analysis.core import ProjectIndex
from sirius_tpu.analysis.dataflow import DEV, DeviceModel

MANIFEST_NAME = "TRANSFER_BUDGET.json"


def load_manifest(project: ProjectIndex) -> dict | None:
    path = os.path.join(project.root, MANIFEST_NAME)
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


@dataclasses.dataclass
class _BodySpan:
    """A line-range region (an ``if`` body without its ``else``);
    duck-types the ``lineno``/``end_lineno`` the attributor needs."""

    lineno: int
    end_lineno: int
    col_offset: int = 0


def _match_regions(fn_node: ast.AST, kind: str) -> list[ast.AST]:
    if kind == "body":
        return [fn_node]
    if kind == "loops":
        return [n for n in ast.walk(fn_node)
                if isinstance(n, (ast.For, ast.While, ast.AsyncFor))]
    if kind.startswith("with:"):
        name = kind[5:]
        out = []
        for n in ast.walk(fn_node):
            if not isinstance(n, (ast.With, ast.AsyncWith)):
                continue
            for item in n.items:
                ce = item.context_expr
                if (isinstance(ce, ast.Call) and ce.args
                        and isinstance(ce.args[0], ast.Constant)
                        and ce.args[0].value == name):
                    out.append(n)
                    break
        return out
    if kind.startswith("if:") or kind.startswith("loop-if:"):
        in_loop = kind.startswith("loop-if:")
        cond = kind.split(":", 1)[1]
        exact = not cond.isidentifier()
        loops = [(n.lineno, n.end_lineno) for n in ast.walk(fn_node)
                 if isinstance(n, (ast.For, ast.While, ast.AsyncFor))]
        out = []
        for n in ast.walk(fn_node):
            if not isinstance(n, ast.If):
                continue
            if exact:
                if ast.unparse(n.test) != cond:
                    continue
            elif not any(isinstance(x, ast.Name) and x.id == cond
                         for x in ast.walk(n.test)):
                continue
            inside = any(lo < n.lineno <= hi for lo, hi in loops)
            if inside == in_loop:
                # only the guarded body: the else branch is the opposite
                # path and must not be charged to this guard's budget
                out.append(_BodySpan(n.body[0].lineno,
                                     n.body[-1].end_lineno))
        return out
    return []


def _span(node: ast.AST) -> tuple[int, int]:
    return (getattr(node, "lineno", 1),
            getattr(node, "end_lineno", getattr(node, "lineno", 1)))


def analyze(project: ProjectIndex, manifest: dict | None = None) -> list:
    """Evaluate every manifest region; returns (cached) region records:
    ``{entry, fi, nodes, counted, allowed_hits, stale_allowed, stale}``
    where ``counted`` is the list of budget-relevant crossings."""
    cached = getattr(project, "_transfer_budget_report", None)
    if cached is not None and manifest is None:
        return cached
    manifest = manifest if manifest is not None else load_manifest(project)
    report: list[dict] = []
    if not manifest:
        project._transfer_budget_report = report
        return report
    model = DeviceModel.of(project)
    for entry in manifest.get("regions", []):
        mi = project.by_relpath.get(entry.get("path", ""))
        fi = mi.functions.get(entry.get("function", "")) if mi else None
        rec = {"entry": entry, "fi": fi, "nodes": [], "counted": [],
               "allowed_hits": {p: 0 for p in entry.get("allowed", [])},
               "stale": False}
        report.append(rec)
        if fi is None:
            rec["stale"] = True
            continue
        rec["nodes"] = _match_regions(fi.node, entry.get("kind", "body"))
        if not rec["nodes"]:
            rec["stale"] = True

    # innermost-region attribution across all entries of one function
    by_fn: dict[tuple, list[dict]] = {}
    for rec in report:
        if rec["fi"] is not None and rec["nodes"]:
            by_fn.setdefault(rec["fi"].key, []).append(rec)
    for key, recs in by_fn.items():
        fi = recs[0]["fi"]
        fctx = fi.module.fctx
        for crossing in model.crossings(fi):
            if DEV not in crossing.origins:
                # parameter-origin crossings are summary inputs: they
                # only become transfers at call sites that pass device
                # values, where they surface as "call" crossings
                continue
            line = getattr(crossing.node, "lineno", 0)
            best = None  # (span size, rec)
            for rec in recs:
                for node in rec["nodes"]:
                    lo, hi = _span(node)
                    if lo <= line <= hi and (
                            best is None or hi - lo < best[0]):
                        best = (hi - lo, rec)
            if best is None:
                continue
            rec = best[1]
            text = fctx.line_text(line)
            allowed = None
            for pat in rec["allowed_hits"]:
                if pat in text or pat in crossing.detail:
                    allowed = pat
                    break
            if allowed is not None:
                rec["allowed_hits"][allowed] += 1
            else:
                rec["counted"].append(crossing)
    project._transfer_budget_report = report
    return report


def budget_report(project: ProjectIndex,
                  manifest: dict | None = None) -> list[dict]:
    """JSON-ready view of :func:`analyze` (tests pin this shape)."""
    out = []
    for rec in analyze(project, manifest):
        e = rec["entry"]
        out.append({
            "path": e.get("path"), "function": e.get("function"),
            "kind": e.get("kind"), "budget": e.get("budget", 0),
            "stale": rec["stale"],
            "count": len(rec["counted"]),
            "crossings": [
                {"line": getattr(c.node, "lineno", 0), "kind": c.kind,
                 "detail": c.detail} for c in rec["counted"]],
            "allowed_hits": dict(rec["allowed_hits"]),
        })
    return out


class TransferBudget:
    """A declared region contains more device→host crossings than its
    budget — the fused-SCF one-readback-per-iteration contract (or a
    zero-transfer hot region) is broken at the flagged line."""

    name = "transfer-budget"

    def run(self, project: ProjectIndex):
        for rec in analyze(project):
            if rec["stale"]:
                continue
            entry, fi = rec["entry"], rec["fi"]
            budget = int(entry.get("budget", 0))
            counted = sorted(
                rec["counted"],
                key=lambda c: getattr(c.node, "lineno", 0))
            for c in counted[budget:]:
                yield project.finding(
                    self.name, fi, c.node,
                    f"device->host crossing ({c.detail}) exceeds the "
                    f"budget of {budget} for region "
                    f"`{entry.get('kind')}` of `{fi.qualname}` "
                    f"(TRANSFER_BUDGET.json)")


class TransferStaleRegion:
    """A manifest entry naming a function or region that no longer
    exists — the budget it declares protects nothing."""

    name = "transfer-stale-region"

    def run(self, project: ProjectIndex):
        for rec in analyze(project):
            if not rec["stale"]:
                continue
            entry = rec["entry"]
            mi = project.by_relpath.get(entry.get("path", ""))
            fctx = mi.fctx if mi else (
                project.files[0] if project.files else None)
            if fctx is None:
                continue
            node = rec["fi"].node if rec["fi"] is not None else None
            yield project.finding(
                self.name, fctx, node,
                f"TRANSFER_BUDGET.json region `{entry.get('kind')}` of "
                f"`{entry.get('path')}::{entry.get('function')}` matches "
                f"nothing in the tree; update or drop the entry")


class TransferStaleAllowance:
    """An ``allowed`` pattern that exempted no crossing — either the
    sanctioned readback was removed (tighten the budget) or the pattern
    is a typo silently allowing nothing."""

    name = "transfer-stale-allowance"

    def run(self, project: ProjectIndex):
        for rec in analyze(project):
            if rec["stale"] or rec["fi"] is None:
                continue
            entry, fi = rec["entry"], rec["fi"]
            for pat, hits in sorted(rec["allowed_hits"].items()):
                if hits == 0:
                    yield project.finding(
                        self.name, fi, rec["nodes"][0],
                        f"allowed pattern \"{pat}\" in region "
                        f"`{entry.get('kind')}` of `{fi.qualname}` "
                        f"matches no crossing; drop it from "
                        f"TRANSFER_BUDGET.json")


RULES = (TransferBudget, TransferStaleRegion, TransferStaleAllowance)
