"""Recompile-hazard rules: trace signatures that vary per call.

A retrace storm is the quietest way to lose a TPU: nothing is wrong,
the answers are right, and every step pays a fresh trace+lower+compile
(SERVE_BENCH.json's 220 backend compiles at a 0.90 cache hit rate is
what that smells like). These rules flag the static patterns that
*must* retrace:

- ``recompile-jit-in-loop`` — a ``jax.jit``/``pmap`` wrapper built
  inside a loop body discards jit's compile cache every iteration.
  Builders that only run on a cache miss (the
  ``exec_cache.get(sig, lambda: jax.jit(...))`` idiom from
  serve/cache.py) are exempt: the lambda body is not loop-executed.
- ``recompile-unstable-static`` — a value that provably varies per
  call (an enclosing loop variable, ``time.*``/``random.*``/``uuid.*``
  results) passed at a ``static_argnums``/``static_argnames`` position:
  every distinct value is a distinct executable.
- ``cache-key-trace-constant`` — the cross-check with serve/cache.py's
  executable keys: for a class that routes a jitted ``self.<impl>``
  through ``ExecutableCache`` (``self.X = cache.get(sig, lambda:
  jax.jit(self.<impl>))``) and declares its key via a
  ``_trace_signature()`` method, every ``self.<attr>`` the impl reads
  is baked into the traced program as a constant — so any read attr
  missing from the signature means two instances that differ only in
  that attr would *share an executable and silently compute with the
  wrong constant*. The analysis and the cache share one definition of
  "same executable": the signature tuple.
"""

from __future__ import annotations

import ast

from sirius_tpu.analysis.core import (
    FunctionInfo,
    ProjectIndex,
    _JIT_WRAPPERS,
    call_name,
    dotted_name,
)
from sirius_tpu.analysis.dataflow import DeviceModel
from sirius_tpu.analysis.jaxrules import (
    _int_elements,
    _local_jit_bindings,
    _str_elements,
)

_VARYING_CALL_PREFIXES = ("time.", "random.", "uuid.", "np.random.",
                          "numpy.random.", "secrets.", "os.urandom")


def _loops_containing(fn_node: ast.AST):
    """(loop_node, set of descendant nodes excluding lambda/def bodies)."""
    out = []
    for node in ast.walk(fn_node):
        if not isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
            continue
        inside: set[int] = set()
        stack = list(node.body) + list(node.orelse)
        while stack:
            n = stack.pop()
            inside.add(id(n))
            if isinstance(n, (ast.Lambda, ast.FunctionDef,
                              ast.AsyncFunctionDef)):
                continue  # deferred bodies don't execute per iteration
            stack.extend(ast.iter_child_nodes(n))
        out.append((node, inside))
    return out


def _loop_vars(fn_node: ast.AST) -> set[str]:
    """Names bound as loop targets anywhere in the function."""
    out: set[str] = set()
    for node in ast.walk(fn_node):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    out.add(n.id)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                for n in ast.walk(gen.target):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
    return out


class RecompileJitInLoop:
    """``jax.jit(...)`` evaluated inside a loop body: the fresh wrapper
    has an empty compile cache, so every iteration retraces and
    recompiles. Hoist the jit out of the loop (or route it through an
    ExecutableCache builder lambda, which this rule exempts)."""

    name = "recompile-jit-in-loop"

    def run(self, project: ProjectIndex):
        for fi in project.iter_functions():
            loops = _loops_containing(fi.node)
            if not loops:
                continue
            for node in ast.walk(fi.node):
                if not (isinstance(node, ast.Call)
                        and call_name(node) in _JIT_WRAPPERS):
                    continue
                if any(id(node) in inside for _, inside in loops):
                    yield project.finding(
                        self.name, fi, node,
                        f"`{call_name(node)}(...)` built inside a loop "
                        f"in `{fi.qualname}` retraces every iteration; "
                        f"hoist it (or build it in a cache-miss lambda)")


class RecompileUnstableStatic:
    """A per-call-varying value at a static position: jit hashes static
    args into the executable key, so a loop index or timestamp there
    means one fresh compile per call — a retrace storm by construction."""

    name = "recompile-unstable-static"

    def _varying_reason(self, expr: ast.AST,
                        loop_vars: set[str]) -> str | None:
        for n in ast.walk(expr):
            if (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
                    and n.id in loop_vars):
                return f"loop variable `{n.id}`"
            if isinstance(n, ast.Call):
                d = call_name(n)
                if d and (d.startswith(_VARYING_CALL_PREFIXES)
                          or d in ("id",)):
                    return f"per-call-varying `{d}()`"
        return None

    def _static_positions(self, project: ProjectIndex,
                          fi: FunctionInfo):
        """callable-name -> (static positions, static names) visible
        from ``fi``: local jit bindings plus resolved jit seeds."""
        local: dict[str, tuple[list[int], list[str]]] = {}
        for tgt, kwargs, _ in _local_jit_bindings(fi.node):
            p = _int_elements(kwargs.get("static_argnums",
                                         ast.Constant(value=None)))
            n = _str_elements(kwargs.get("static_argnames",
                                         ast.Constant(value=None)))
            if p or n:
                local[tgt] = (p, n)
        return local

    def run(self, project: ProjectIndex):
        project.jit_reachable()  # populate jit_kwargs on seeds
        seeded: dict[tuple, tuple[list[int], list[str]]] = {}
        for fi in project.iter_functions():
            if fi.jit_kwargs:
                p = _int_elements(fi.jit_kwargs.get(
                    "static_argnums", ast.Constant(value=None)))
                n = _str_elements(fi.jit_kwargs.get(
                    "static_argnames", ast.Constant(value=None)))
                if p or n:
                    seeded[fi.key] = (p, n)
        for fi in project.iter_functions():
            loop_vars = _loop_vars(fi.node)
            local = self._static_positions(project, fi)
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                d = call_name(node)
                if not d:
                    continue
                info = local.get(d)
                if info is None:
                    for tgt in project._resolve_call(fi.module, fi.cls, d):
                        info = seeded.get(tgt.key)
                        if info:
                            break
                if not info:
                    continue
                pos, names = info
                for i, a in enumerate(node.args):
                    if i not in pos:
                        continue
                    why = self._varying_reason(a, loop_vars)
                    if why:
                        yield project.finding(
                            self.name, fi, a,
                            f"{why} at static position {i} of `{d}` in "
                            f"`{fi.qualname}`: one recompile per call")
                for k in node.keywords:
                    if k.arg not in names:
                        continue
                    why = self._varying_reason(k.value, loop_vars)
                    if why:
                        yield project.finding(
                            self.name, fi, k.value,
                            f"{why} for static arg `{k.arg}` of `{d}` in "
                            f"`{fi.qualname}`: one recompile per call")


class CacheKeyTraceConstant:
    """A ``self.<attr>`` read by a cache-shared jitted impl but missing
    from the class's ``_trace_signature()``: the attr is baked into the
    executable as a constant, yet two instances differing only in it
    produce equal cache keys — the second silently reuses the first's
    program with the wrong constant."""

    name = "cache-key-trace-constant"

    def _self_attr_reads(self, mi, cls: str, method: str,
                         seen: set[str]) -> set[str]:
        """self.<attr> Loads in ``cls.method``, transitively through
        same-class method calls; attribute names used as call targets
        (``self.m(...)``) recurse instead of counting as reads."""
        out: set[str] = set()
        fi = mi.functions.get(f"{cls}.{method}")
        if fi is None or method in seen:
            return out
        seen.add(method)
        call_funcs = {id(n.func) for n in ast.walk(fi.node)
                      if isinstance(n, ast.Call)}
        for node in ast.walk(fi.node):
            if not (isinstance(node, ast.Attribute)
                    and isinstance(node.ctx, ast.Load)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                continue
            if id(node) in call_funcs:
                if f"{cls}.{node.attr}" in mi.functions:
                    out |= self._self_attr_reads(mi, cls, node.attr, seen)
                continue
            out.add(node.attr)
        return out

    def run(self, project: ProjectIndex):
        model = DeviceModel.of(project)
        for (mod, cls, attr), impl in sorted(model.jit_attr_impl.items()):
            mi = project.modules.get(mod)
            if mi is None:
                continue
            sig_fi = mi.functions.get(f"{cls}._trace_signature")
            impl_fi = mi.functions.get(f"{cls}.{impl}")
            if sig_fi is None or impl_fi is None:
                continue
            sig_attrs = self._self_attr_reads(
                mi, cls, "_trace_signature", set())
            reads = self._self_attr_reads(mi, cls, impl, set())
            jit_attrs = model.jit_attrs.get((mod, cls), set())
            for a in sorted(reads - sig_attrs - jit_attrs):
                node = None
                for n in ast.walk(impl_fi.node):
                    if (isinstance(n, ast.Attribute) and n.attr == a
                            and isinstance(n.value, ast.Name)
                            and n.value.id == "self"):
                        node = n
                        break
                yield project.finding(
                    self.name, impl_fi, node,
                    f"`self.{a}` read by jitted `{cls}.{impl}` (bound to "
                    f"`self.{attr}`) but absent from "
                    f"`{cls}._trace_signature()`: equal cache keys would "
                    f"reuse an executable with the wrong baked-in value")


RULES = (RecompileJitInLoop, RecompileUnstableStatic,
         CacheKeyTraceConstant)
