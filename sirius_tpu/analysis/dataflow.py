"""Interprocedural jit-boundary dataflow shared by the v2 rule families.

Everything here is still pure ``ast`` — no imports of analysed code —
but unlike the per-function taint in jaxrules.py the model is
*summary-based and interprocedural*:

- **Origin sets.** An expression evaluates to a set of origin tokens:
  ``"dev"`` (flows from a device producer — ``jnp.*``/``lax.*`` calls,
  a jitted binding, an attribute spelled ``*_dev``) and/or ``"p<i>"``
  (flows from the function's i-th parameter). Empty set = host value.
- **Function summaries.** A fixpoint over the resolved project call
  graph computes, per function, its *return origins* (does it return a
  device value; which parameters flow through to the return) and its
  *crossed params* (which parameters it moves to host internally). Call
  sites substitute actual-argument origins into the summary, so
  ``fused_out = fused.step(carry)`` is device-tainted because
  ``FusedScf.step`` returns the output of a ``self._step`` jit binding
  three modules away.
- **Instance typing.** ``x = ClassName(...)`` (locals) and
  ``self.a = ClassName(...)`` (attrs) resolve through the import map so
  ``x.method(...)`` calls bind to ``ClassName.method`` cross-module.
- **Crossings.** A device→host crossing is recorded where a tainted
  value meets ``float()``/``int()``/``bool()``, ``.item()``/
  ``.tolist()``, ``np.asarray``/``np.array``, ``jax.device_get``,
  implicit bool coercion (``if``/``while``/``not``/``and``/``or``), a
  Python ``for`` over a device array, or a call whose summary says the
  callee crosses that parameter. ``.block_until_ready()`` is a *fence*,
  not a transfer: it keeps its origins and records nothing — matching
  the runtime ``jax.transfer_guard`` contract the budget rule mirrors.

The evaluator makes two passes per function: pass one only grows the
local environment (so loop-carried assignments converge), pass two
records crossings. Precision is deliberately modest — no path
sensitivity, no container element tracking — but it is *sound enough in
practice* to prove the fused-SCF one-readback contract and cheap enough
to stay inside the lint runtime budget.
"""

from __future__ import annotations

import ast
import dataclasses

from sirius_tpu.analysis.core import (
    FunctionInfo,
    ModuleInfo,
    ProjectIndex,
    _JIT_WRAPPERS,
    call_name,
    dotted_name,
)

DEV = "dev"

_DEVICE_PREFIXES = ("jnp.", "jax.numpy.", "lax.", "jax.lax.",
                    "jax.scipy.", "jsp.", "jax.nn.")
_DEVICE_CALLS = {"jax.device_put", "device_put"}
_CAST_FNS = {"float", "int", "bool", "complex"}
_NP_CROSSERS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                "np.copy", "numpy.copy"}
_DEVICE_GET = {"jax.device_get", "device_get"}
_SYNC_METHODS = {"item", "tolist"}
_FENCE_METHODS = {"block_until_ready"}
# host-returning builtins: pass device values without moving them
# (len/shape are metadata; str/repr only appear on host paths)
_HOST_FNS = {"len", "range", "print", "str", "repr", "format",
             "isinstance", "hasattr", "getattr", "type", "id",
             "enumerate", "zip", "list", "tuple", "dict", "set",
             "sorted", "reversed"}


@dataclasses.dataclass
class Crossing:
    """One device→host movement, attributable to a source line."""

    node: ast.AST
    kind: str    # cast | asarray | item | device_get | bool | iter | call
    detail: str  # the call/expression text that moves the data
    origins: frozenset


def _param_names(node: ast.AST) -> list[str]:
    a = getattr(node, "args", None)
    if a is None:
        return []
    names = [p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
    return names


class DeviceModel:
    """Project-wide device/host dataflow summaries (built lazily once
    per ProjectIndex; the three rule families share one instance)."""

    _CACHE_ATTR = "_dataflow_device_model"

    @classmethod
    def of(cls, project: ProjectIndex) -> "DeviceModel":
        model = getattr(project, cls._CACHE_ATTR, None)
        if model is None:
            model = cls(project)
            setattr(project, cls._CACHE_ATTR, model)
        return model

    def __init__(self, project: ProjectIndex):
        self.project = project
        project.jit_reachable()  # populate seeds/jit_kwargs
        # (module, class) -> attrs bound to jitted callables
        # (``self.X = ... jax.jit(...) ...`` anywhere in the class)
        self.jit_attrs: dict[tuple[str, str], set[str]] = {}
        # (module, class, attr) -> impl method name it wraps, when the
        # binding's jit call wraps ``self.<impl>`` (compilerules keys
        # the trace-signature cross-check on this)
        self.jit_attr_impl: dict[tuple[str, str, str], str] = {}
        self._scan_jit_attrs()
        # per-function summaries, keyed by FunctionInfo.key
        self.return_origins: dict[tuple, frozenset] = {}
        self.crossed_params: dict[tuple, frozenset] = {}
        self._inst_types: dict[tuple, dict[str, tuple[ModuleInfo, str]]] = {}
        self._attr_types: dict[tuple[str, str, str],
                               tuple[ModuleInfo, str]] = {}
        self._scan_instance_attrs()
        self._fixpoint()
        self._crossings: dict[tuple, list[Crossing]] = {}

    # -- structural scans --------------------------------------------------

    def _scan_jit_attrs(self) -> None:
        for fi in self.project.iter_functions():
            if not fi.cls:
                continue
            for node in ast.walk(fi.node):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1):
                    continue
                tgt = dotted_name(node.targets[0])
                if not tgt or not tgt.startswith("self."):
                    continue
                for sub in ast.walk(node.value):
                    if (isinstance(sub, ast.Call)
                            and call_name(sub) in _JIT_WRAPPERS):
                        attr = tgt[5:]
                        self.jit_attrs.setdefault(
                            (fi.module.name, fi.cls), set()).add(attr)
                        if sub.args:
                            d = dotted_name(sub.args[0])
                            if d and d.startswith("self."):
                                self.jit_attr_impl[
                                    (fi.module.name, fi.cls, attr)
                                ] = d[5:]
                        break

    def _resolve_class(self, mi: ModuleInfo,
                       name: str) -> tuple[ModuleInfo, str] | None:
        """``ClassName`` / ``mod.ClassName`` -> defining (module, class)."""
        if "." not in name:
            if name in mi.classes:
                return (mi, name)
            tgt = mi.imports.get(name)
            if tgt and "." in tgt:
                m, c = tgt.rsplit(".", 1)
                if m in self.project.modules and (
                        c in self.project.modules[m].classes):
                    return (self.project.modules[m], c)
            return None
        head, rest = name.split(".", 1)
        base = mi.imports.get(head, head)
        parts = f"{base}.{rest}".split(".")
        for i in range(len(parts) - 1, 0, -1):
            m = ".".join(parts[:i])
            if m in self.project.modules:
                c = ".".join(parts[i:])
                if c in self.project.modules[m].classes:
                    return (self.project.modules[m], c)
                break
        return None

    def _scan_instance_attrs(self) -> None:
        """``self.a = ClassName(...)`` -> (module, cls, a) instance type."""
        for fi in self.project.iter_functions():
            if not fi.cls:
                continue
            for node in ast.walk(fi.node):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.value, ast.Call)):
                    continue
                tgt = dotted_name(node.targets[0])
                cn = call_name(node.value)
                if not tgt or not tgt.startswith("self.") or not cn:
                    continue
                hit = self._resolve_class(fi.module, cn)
                if hit:
                    self._attr_types[
                        (fi.module.name, fi.cls, tgt[5:])] = hit

    def instance_types(self, fi: FunctionInfo) -> dict:
        """Local-variable -> (module, class) bindings from
        ``x = ClassName(...)`` assignments inside ``fi``."""
        cached = self._inst_types.get(fi.key)
        if cached is not None:
            return cached
        out: dict[str, tuple[ModuleInfo, str]] = {}
        for node in ast.walk(fi.node):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                continue
            cn = call_name(node.value)
            if not cn:
                continue
            hit = self._resolve_class(fi.module, cn)
            if hit:
                out[node.targets[0].id] = hit
        self._inst_types[fi.key] = out
        return out

    # -- call resolution with instance typing ------------------------------

    def resolve_call(self, fi: FunctionInfo,
                     name: str) -> list[FunctionInfo]:
        out = self.project._resolve_call(fi.module, fi.cls, name)
        if out or "." not in name:
            return out
        head, rest = name.split(".", 1)
        hit = self.instance_types(fi).get(head)
        if hit is None and head == "self" and fi.cls and "." in rest:
            # self.a.method() through a typed instance attribute
            a, rest2 = rest.split(".", 1)
            hit2 = self._attr_types.get((fi.module.name, fi.cls, a))
            if hit2:
                m, c = hit2
                q = f"{c}.{rest2}"
                if q in m.functions:
                    return [m.functions[q]]
            return []
        if hit is None:
            return []
        m, c = hit
        q = f"{c}.{rest}"
        return [m.functions[q]] if q in m.functions else []

    def is_jit_binding_call(self, fi: FunctionInfo, name: str) -> bool:
        """``self.X(...)`` where X is a jit attr of fi's class, or a
        local ``g = jax.jit(...)`` binding name."""
        if name.startswith("self.") and fi.cls:
            attr = name[5:].split(".")[0]
            return attr in self.jit_attrs.get(
                (fi.module.name, fi.cls), ())
        return name in self._local_jit_names(fi)

    def _local_jit_names(self, fi: FunctionInfo) -> set[str]:
        names = getattr(fi, "_local_jit_names", None)
        if names is None:
            names = set()
            for node in ast.walk(fi.node):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and isinstance(node.value, ast.Call)
                        and call_name(node.value) in _JIT_WRAPPERS):
                    names.add(node.targets[0].id)
            fi._local_jit_names = names
        return names

    # -- summary fixpoint --------------------------------------------------

    def _fixpoint(self) -> None:
        fns = list(self.project.iter_functions())
        for fi in fns:
            self.return_origins[fi.key] = frozenset()
            self.crossed_params[fi.key] = frozenset()
        for _ in range(4):  # summaries stabilise in 2-3 rounds
            changed = False
            for fi in fns:
                scan = _FunctionScan(self, fi)
                scan.run()
                ret = frozenset(scan.return_origins)
                crossed = frozenset(
                    o for c in scan.crossings for o in c.origins
                    if o != DEV)
                if ret != self.return_origins[fi.key]:
                    self.return_origins[fi.key] = ret
                    changed = True
                if crossed != self.crossed_params[fi.key]:
                    self.crossed_params[fi.key] = crossed
                    changed = True
            if not changed:
                break

    # -- per-function results ----------------------------------------------

    def crossings(self, fi: FunctionInfo) -> list[Crossing]:
        cached = self._crossings.get(fi.key)
        if cached is None:
            scan = _FunctionScan(self, fi)
            scan.run()
            cached = scan.crossings
            self._crossings[fi.key] = cached
        return cached


class _FunctionScan:
    """Two-pass forward evaluation of one function body: pass one only
    grows the environment (loop-carried assignments), pass two records
    crossings and return origins."""

    def __init__(self, model: DeviceModel, fi: FunctionInfo):
        self.model = model
        self.fi = fi
        self.env: dict[str, frozenset] = {}
        self.crossings: list[Crossing] = []
        self.return_origins: set[str] = set()
        self._emitting = False
        for i, p in enumerate(_param_names(fi.node)):
            self.env[p] = frozenset({f"p{i}"})

    def run(self) -> None:
        node = self.fi.node
        if isinstance(node, ast.Lambda):
            self._emitting = True
            self.return_origins |= self._eval(node.body)
            return
        self._emitting = False
        self._visit_block(node.body)
        self._emitting = True
        self.crossings = []
        self.return_origins = set()
        self._visit_block(node.body)

    # -- statements --------------------------------------------------------

    def _visit_block(self, stmts) -> None:
        for s in stmts:
            self._visit(s)

    def _visit(self, node: ast.stmt) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested scopes analysed as their own functions
        if isinstance(node, ast.Assign):
            o = self._eval(node.value)
            for t in node.targets:
                self._bind(t, o)
        elif isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self._bind(node.target, self._eval(node.value))
        elif isinstance(node, ast.AugAssign):
            o = self._eval(node.value)
            if isinstance(node.target, ast.Name):
                prev = self.env.get(node.target.id, frozenset())
                self.env[node.target.id] = prev | o
        elif isinstance(node, ast.Return):
            if node.value is not None:
                self.return_origins |= self._eval(node.value)
        elif isinstance(node, ast.Expr):
            self._eval(node.value)
        elif isinstance(node, (ast.If, ast.While)):
            self._test(node.test)
            self._visit_block(node.body)
            self._visit_block(node.orelse)
        elif isinstance(node, ast.For):
            o = self._eval(node.iter)
            if o and self._emitting:
                self._cross(node.iter, "iter",
                            "Python for over a device value", o)
            self._bind(node.target, o)
            self._visit_block(node.body)
            self._visit_block(node.orelse)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                self._eval(item.context_expr)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, frozenset())
            self._visit_block(node.body)
        elif isinstance(node, ast.Try):
            self._visit_block(node.body)
            for h in node.handlers:
                self._visit_block(h.body)
            self._visit_block(node.orelse)
            self._visit_block(node.finalbody)
        elif isinstance(node, ast.Assert):
            self._test(node.test)
        elif isinstance(node, (ast.Raise, ast.Delete, ast.Global,
                               ast.Nonlocal, ast.Pass, ast.Break,
                               ast.Continue, ast.Import, ast.ImportFrom)):
            if isinstance(node, ast.Raise) and node.exc is not None:
                self._eval(node.exc)

    def _bind(self, target: ast.AST, origins: frozenset) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = origins
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, origins)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, origins)
        # attribute/subscript stores: drop (no heap model)

    def _test(self, test: ast.AST) -> None:
        """Implicit bool coercion: a tainted branch condition is a
        device->host sync. ``x is None`` identity tests are static."""
        if (isinstance(test, ast.Compare)
                and all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in test.ops)):
            return
        o = self._eval(test)
        if o and self._emitting:
            self._cross(test, "bool",
                        "implicit bool() on a device value", o)

    # -- expressions -------------------------------------------------------

    def _cross(self, node: ast.AST, kind: str, detail: str,
               origins: frozenset) -> None:
        self.crossings.append(Crossing(node, kind, detail,
                                       frozenset(origins)))

    def _eval(self, e: ast.AST) -> frozenset:
        empty = frozenset()
        if e is None or isinstance(e, ast.Constant):
            return empty
        if isinstance(e, ast.Name):
            return self.env.get(e.id, empty)
        if isinstance(e, ast.Attribute):
            if e.attr.endswith(("_dev", "_device")):
                return frozenset({DEV})
            if e.attr in ("dtype", "shape", "ndim", "size"):
                return empty  # array metadata: host-side, no transfer
            return self._eval(e.value)
        if isinstance(e, ast.Subscript):
            return self._eval(e.value) | self._eval(e.slice)
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            o = empty
            for x in e.elts:
                o |= self._eval(x)
            return o
        if isinstance(e, ast.Dict):
            o = empty
            for k, v in zip(e.keys, e.values):
                if k is not None:
                    o |= self._eval(k)
                o |= self._eval(v)
            return o
        if isinstance(e, ast.BinOp):
            return self._eval(e.left) | self._eval(e.right)
        if isinstance(e, ast.UnaryOp):
            o = self._eval(e.operand)
            if isinstance(e.op, ast.Not) and o and self._emitting:
                self._cross(e, "bool",
                            "`not` on a device value", o)
                return empty
            return o
        if isinstance(e, ast.BoolOp):
            # short-circuiting coerces each operand to bool; record per
            # tainted operand and return host (the enclosing test must
            # not double-count)
            for v in e.values:
                vo = self._eval(v)
                if vo and self._emitting:
                    self._cross(v, "bool",
                                "and/or on a device value", vo)
            return empty
        if isinstance(e, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops):
                return empty  # identity: static at trace/host time
            o = self._eval(e.left)
            for c in e.comparators:
                o |= self._eval(c)
            return o
        if isinstance(e, ast.IfExp):
            self._test(e.test)
            return self._eval(e.body) | self._eval(e.orelse)
        if isinstance(e, ast.Starred):
            return self._eval(e.value)
        if isinstance(e, (ast.JoinedStr, ast.FormattedValue)):
            for sub in ast.iter_child_nodes(e):
                self._eval(sub)
            return empty
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                          ast.DictComp)):
            o = empty
            for gen in e.generators:
                go = self._eval(gen.iter)
                self._bind(gen.target, go)
                o |= go
            if isinstance(e, ast.DictComp):
                o |= self._eval(e.key) | self._eval(e.value)
            else:
                o |= self._eval(e.elt)
            return o
        if isinstance(e, ast.Lambda):
            return empty
        if isinstance(e, ast.NamedExpr):
            o = self._eval(e.value)
            self._bind(e.target, o)
            return o
        if isinstance(e, ast.Await):
            return self._eval(e.value)
        if isinstance(e, ast.Call):
            return self._eval_call(e)
        return empty

    def _eval_call(self, call: ast.Call) -> frozenset:
        empty = frozenset()
        name = call_name(call) or ""
        arg_origins = [self._eval(a) for a in call.args]
        kw_origins = {k.arg: self._eval(k.value) for k in call.keywords}
        all_in = empty
        for o in arg_origins:
            all_in |= o
        for o in kw_origins.values():
            all_in |= o

        # explicit crossings --------------------------------------------
        if name in _CAST_FNS:
            if call.args and arg_origins[0] and self._emitting:
                self._cross(call, "cast", f"{name}()", arg_origins[0])
            return empty
        if name in _NP_CROSSERS:
            if call.args and arg_origins[0] and self._emitting:
                self._cross(call, "asarray", f"{name}()", arg_origins[0])
            return empty
        if name in _DEVICE_GET:
            if all_in and self._emitting:
                self._cross(call, "device_get", f"{name}()", all_in)
            return empty
        if isinstance(call.func, ast.Attribute):
            base_o = self._eval(call.func.value)
            if call.func.attr in _SYNC_METHODS:
                if base_o and self._emitting:
                    self._cross(call, "item",
                                f".{call.func.attr}()", base_o)
                return empty
            if call.func.attr in _FENCE_METHODS:
                return base_o  # fence: synchronises, moves nothing

        # device producers ----------------------------------------------
        if (name.startswith(_DEVICE_PREFIXES) or name in _DEVICE_CALLS
                or self.model.is_jit_binding_call(self.fi, name)):
            return frozenset({DEV})

        if name in _HOST_FNS:
            return empty

        # project-resolved calls: substitute summaries ---------------------
        cands = self.model.resolve_call(self.fi, name) if name else []
        if cands:
            out: set[str] = set()
            for cand in cands:
                pnames = _param_names(cand.node)
                is_method = bool(cand.cls) and pnames[:1] == ["self"]
                off = 1 if is_method and "." in name else 0

                def actual(idx: int) -> frozenset:
                    j = idx - off
                    if 0 <= j < len(arg_origins):
                        return arg_origins[j]
                    if 0 <= idx < len(pnames):
                        return kw_origins.get(pnames[idx], empty)
                    return empty

                for tok in self.model.return_origins.get(cand.key, ()):
                    if tok == DEV:
                        out.add(DEV)
                    elif tok.startswith("p"):
                        out |= actual(int(tok[1:]))
                for tok in self.model.crossed_params.get(cand.key, ()):
                    idx = int(tok[1:])
                    o = actual(idx)
                    if o and self._emitting:
                        pn = pnames[idx] if idx < len(pnames) else tok
                        self._cross(
                            call, "call",
                            f"{name}() moves its `{pn}` argument to "
                            f"host", o)
            return frozenset(out)

        # unresolved: conservative pass-through
        return all_in
