"""Sharding-consistency rules and the per-driver sharding inventory.

A static mesh/axis model built from every ``Mesh(...)``,
``NamedSharding``, ``PartitionSpec``/``P``, ``shard_map``,
``with_sharding_constraint`` and named-axis collective in the tree:

- ``shard-unknown-axis`` — an axis name used in a PartitionSpec or as a
  collective's ``axis_name`` that no ``Mesh(...)`` in the project
  declares. GSPMD raises at trace time *if* the code path runs; decks
  that never take the path ship the typo silently.
- ``shard-axis-mismatch`` — a ``NamedSharding(mesh, P(...))`` or
  ``shard_map(..., mesh=mesh, ...)`` whose spec names an axis that the
  *specific* mesh bound to that variable does not declare (the axis may
  exist on some other mesh — that is exactly the hazard: a "k" spec on
  the "g" mesh).
- ``shard-constraint-in-loop`` — ``with_sharding_constraint`` inside a
  loop body of jit-reachable code: every iteration forces GSPMD to
  materialise the constraint, i.e. a potential all-to-all reshard in
  the hot loop.

``sharding_inventory()`` renders the pre-flight artifact the
ExecutionPlan refactor needs (`sirius-lint --report sharding`): one row
per driver — scf, serve, md, relax, campaigns — listing the meshes it
constructs, the axes/specs/constraints/collectives it uses, and its
jit/donation sites, so the five independently-maintained sharding sites
can be diffed at review time instead of in a post-mortem.
"""

from __future__ import annotations

import ast

from sirius_tpu.analysis.core import (
    FunctionInfo,
    ProjectIndex,
    _JIT_WRAPPERS,
    call_name,
    dotted_name,
)

_MESH_CTORS = {"Mesh", "make_mesh"}
_SPEC_CTORS = {"PartitionSpec", "P"}
_COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "all_gather",
                "all_to_all", "ppermute", "pshuffle", "axis_index",
                "psum_scatter"}
_CONSTRAINT = {"with_sharding_constraint"}

DRIVERS = (
    ("scf", "sirius_tpu/dft/scf.py"),
    ("serve", "sirius_tpu/serve/scheduler.py"),
    ("md", "sirius_tpu/md/driver.py"),
    ("relax", "sirius_tpu/dft/relax.py"),
    ("campaigns", "sirius_tpu/campaigns/runner.py"),
)


def _axis_strings(node: ast.AST) -> list[str]:
    """Axis-name string literals inside a spec/axes expression."""
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.append(n.value)
    return out


def _mesh_axes_from_call(call: ast.Call) -> list[str]:
    """Declared axis names of a ``Mesh(devs, ("k", "b"))`` /
    ``axis_names=...`` construction (empty when non-literal)."""
    for k in call.keywords:
        if k.arg == "axis_names":
            return _axis_strings(k.value)
    if len(call.args) >= 2:
        return _axis_strings(call.args[1])
    return []


def _is_ctor(mi, name: str | None, ctors: set[str]) -> bool:
    """True when a dotted call name denotes one of ``ctors``, resolving
    local aliases (``Mesh as _Mesh``, ``PartitionSpec as _P``) through
    the module's import map."""
    if not name:
        return False
    if name.split(".")[-1] in ctors:
        return True
    tgt = mi.imports.get(name) or mi.imports.get(name.split(".")[0])
    return bool(tgt) and tgt.split(".")[-1] in ctors


class MeshModel:
    """Project-wide mesh declarations + per-function mesh variables."""

    _CACHE_ATTR = "_shard_mesh_model"

    @classmethod
    def of(cls, project: ProjectIndex) -> "MeshModel":
        model = getattr(project, cls._CACHE_ATTR, None)
        if model is None:
            model = cls(project)
            setattr(project, cls._CACHE_ATTR, model)
        return model

    def __init__(self, project: ProjectIndex):
        self.project = project
        # every Mesh construction: (fctx, node, axes tuple)
        self.meshes: list[tuple] = []
        # function key -> axes it returns (mesh-producing helpers like
        # make_mesh / production_mesh, incl. (mesh, spec) tuple returns)
        self.producer_axes: dict[tuple, tuple] = {}
        for mi in project.modules.values():
            for node in ast.walk(mi.fctx.tree):
                if (isinstance(node, ast.Call)
                        and _is_ctor(mi, call_name(node), {"Mesh"})):
                    axes = tuple(_mesh_axes_from_call(node))
                    if axes:
                        self.meshes.append((mi.fctx, node, axes))
        for fi in project.iter_functions():
            axes = set()
            for node in ast.walk(fi.node):
                if (isinstance(node, ast.Call)
                        and _is_ctor(fi.module, call_name(node),
                                     {"Mesh"})):
                    axes.update(_mesh_axes_from_call(node))
            if axes:
                self.producer_axes[fi.key] = tuple(sorted(axes))
        # one propagation round: helpers that return another helper's
        # mesh (production_mesh -> make_mesh)
        for _ in range(2):
            changed = False
            for fi in project.iter_functions():
                if fi.key in self.producer_axes:
                    continue
                axes = set()
                for node in ast.walk(fi.node):
                    if not isinstance(node, ast.Call):
                        continue
                    d = call_name(node)
                    if not d:
                        continue
                    for cand in project._resolve_call(
                            fi.module, fi.cls, d):
                        axes.update(self.producer_axes.get(cand.key, ()))
                if axes and any(
                        isinstance(n, ast.Return)
                        for n in ast.walk(fi.node)):
                    self.producer_axes[fi.key] = tuple(sorted(axes))
                    changed = True
            if not changed:
                break
        self.declared_axes = frozenset(
            a for _, _, axes in self.meshes for a in axes) | frozenset(
            a for axes in self.producer_axes.values() for a in axes)

    def local_mesh_vars(self, fi: FunctionInfo) -> dict[str, tuple]:
        """var name -> axes for meshes bound inside ``fi``:
        ``m = Mesh(..., axes)``, ``m = make_mesh(...)`` and the
        ``mesh, spec = production_mesh(...)`` tuple-unpack idiom."""
        out: dict[str, tuple] = {}
        for node in ast.walk(fi.node):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.value, ast.Call)):
                continue
            call, tgt = node.value, node.targets[0]
            d = call_name(call)
            axes: tuple = ()
            if _is_ctor(fi.module, d, {"Mesh"}):
                axes = tuple(_mesh_axes_from_call(call))
            elif d:
                for cand in self.project._resolve_call(
                        fi.module, fi.cls, d):
                    axes = self.producer_axes.get(cand.key, ())
                    if axes:
                        break
            if not axes:
                continue
            if isinstance(tgt, ast.Name):
                out[tgt.id] = axes
            elif (isinstance(tgt, ast.Tuple) and tgt.elts
                  and isinstance(tgt.elts[0], ast.Name)):
                out[tgt.elts[0].id] = axes  # (mesh, spec) unpack
        return out


def _axis_name_args(call: ast.Call) -> list[ast.AST]:
    """The axis-name expression(s) of a collective call."""
    out = [k.value for k in call.keywords if k.arg == "axis_name"]
    d = call_name(call) or ""
    tail = d.split(".")[-1]
    if not out and tail in _COLLECTIVES and len(call.args) >= 2:
        out.append(call.args[1])
    if not out and tail == "axis_index" and call.args:
        out.append(call.args[0])
    return out


class ShardUnknownAxis:
    """An axis name in a PartitionSpec or collective that no Mesh in
    the project declares — a trace-time crash on the paths that run,
    a latent typo on the ones that don't."""

    name = "shard-unknown-axis"

    def run(self, project: ProjectIndex):
        model = MeshModel.of(project)
        if not model.declared_axes:
            return  # no meshes anywhere: nothing to check against
        for fi in project.iter_functions():
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                d = call_name(node)
                if _is_ctor(fi.module, d, _SPEC_CTORS):
                    for a in _axis_strings(node):
                        if a not in model.declared_axes:
                            yield project.finding(
                                self.name, fi, node,
                                f"axis \"{a}\" in PartitionSpec is not "
                                f"declared by any Mesh (declared: "
                                f"{sorted(model.declared_axes)})")
                elif d and d.split(".")[-1] in _COLLECTIVES:
                    for arg in _axis_name_args(node):
                        for a in _axis_strings(arg):
                            if a not in model.declared_axes:
                                yield project.finding(
                                    self.name, fi, node,
                                    f"collective axis_name \"{a}\" is "
                                    f"not declared by any Mesh")


class ShardAxisMismatch:
    """A spec bound to a *specific* mesh variable names an axis that
    mesh does not declare — e.g. a ("k", "b") spec device_put onto the
    "g" FFT mesh. The axis exists somewhere, which is why the global
    unknown-axis check cannot catch it."""

    name = "shard-axis-mismatch"

    def _check(self, project, fi, mesh_axes, call, spec_node):
        for a in _axis_strings(spec_node):
            if a not in mesh_axes:
                yield project.finding(
                    self.name, fi, call,
                    f"axis \"{a}\" not on this mesh (axes: "
                    f"{list(mesh_axes)}); the spec would be rejected "
                    f"at trace time")

    def run(self, project: ProjectIndex):
        model = MeshModel.of(project)
        for fi in project.iter_functions():
            mesh_vars = model.local_mesh_vars(fi)
            if not mesh_vars:
                continue
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                d = call_name(node)
                if _is_ctor(fi.module, d,
                            {"NamedSharding"}) and len(node.args) >= 2:
                    axes = mesh_vars.get(dotted_name(node.args[0]) or "")
                    if axes:
                        yield from self._check(
                            project, fi, axes, node, node.args[1])
                elif d and d.split(".")[-1] in ("shard_map",
                                                "_shard_map"):
                    mesh_kw = next(
                        (k.value for k in node.keywords
                         if k.arg == "mesh"), None)
                    if mesh_kw is None:
                        continue
                    axes = mesh_vars.get(dotted_name(mesh_kw) or "")
                    if not axes:
                        continue
                    for k in node.keywords:
                        if k.arg in ("in_specs", "out_specs"):
                            yield from self._check(
                                project, fi, axes, node, k.value)


class ShardConstraintInLoop:
    """``with_sharding_constraint`` inside a loop of jit-reachable code
    — each iteration pins a layout the compiler must materialise,
    i.e. a standing invitation for a per-iteration reshard."""

    name = "shard-constraint-in-loop"

    def run(self, project: ProjectIndex):
        reach = project.jit_reachable()
        for fi in project.iter_functions():
            if fi.key not in reach:
                continue
            loop_spans = [
                (n.lineno, n.end_lineno)
                for n in ast.walk(fi.node)
                if isinstance(n, (ast.For, ast.While, ast.AsyncFor))]
            if not loop_spans:
                continue
            for node in ast.walk(fi.node):
                if not (isinstance(node, ast.Call)
                        and _is_ctor(fi.module, call_name(node),
                                     _CONSTRAINT)):
                    continue
                line = node.lineno
                if any(lo < line <= hi for lo, hi in loop_spans):
                    yield project.finding(
                        self.name, fi, node,
                        f"with_sharding_constraint inside a loop of "
                        f"jit-reachable `{fi.qualname}`; hoist the "
                        f"constraint or fold it into the carry's "
                        f"sharding")


# ---------------------------------------------------------------------------
# inventory report


def _file_inventory(project: ProjectIndex, relpath: str) -> dict:
    mi = project.by_relpath.get(relpath)
    row: dict = {
        "path": relpath,
        "indexed": mi is not None,
        "meshes": [],
        "partition_specs": [],
        "named_shardings": 0,
        "sharding_constraints": 0,
        "collectives": [],
        "jit_sites": 0,
        "donate_argnums": [],
        "axes_used": [],
    }
    if mi is None:
        return row
    axes_used: set[str] = set()
    specs: set[tuple] = set()
    for node in ast.walk(mi.fctx.tree):
        if not isinstance(node, ast.Call):
            continue
        d = call_name(node)
        if _is_ctor(mi, d, {"Mesh"}):
            axes = _mesh_axes_from_call(node)
            row["meshes"].append({"line": node.lineno, "axes": axes})
            axes_used.update(axes)
        elif _is_ctor(mi, d, _SPEC_CTORS):
            s = tuple(_axis_strings(node))
            specs.add(s)
            axes_used.update(s)
        elif _is_ctor(mi, d, {"NamedSharding"}):
            row["named_shardings"] += 1
        elif _is_ctor(mi, d, _CONSTRAINT):
            row["sharding_constraints"] += 1
        elif d and d.split(".")[-1] in _COLLECTIVES:
            names = [a for arg in _axis_name_args(node)
                     for a in _axis_strings(arg)]
            row["collectives"].append({
                "op": d.split(".")[-1], "line": node.lineno,
                "axes": names})
            axes_used.update(names)
        if d in _JIT_WRAPPERS:
            row["jit_sites"] += 1
            for k in node.keywords:
                if k.arg == "donate_argnums":
                    lits = [n.value for n in ast.walk(k.value)
                            if isinstance(n, ast.Constant)
                            and isinstance(n.value, int)]
                    row["donate_argnums"].append(
                        {"line": node.lineno, "argnums": lits})
    row["partition_specs"] = sorted(list(s) for s in specs)
    row["axes_used"] = sorted(axes_used)
    return row


def sharding_inventory(project: ProjectIndex) -> dict:
    """The five-driver sharding inventory (``--report sharding``)."""
    model = MeshModel.of(project)
    return {
        "version": 1,
        "declared_axes": sorted(model.declared_axes),
        "drivers": {name: _file_inventory(project, rel)
                    for name, rel in DRIVERS},
        "parallel": {
            rel: _file_inventory(project, rel)
            for rel in sorted(
                f.relpath for f in project.files
                if f.relpath.startswith("sirius_tpu/parallel/"))},
    }


RULES = (ShardUnknownAxis, ShardAxisMismatch, ShardConstraintInLoop)
